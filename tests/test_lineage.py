"""Sample-lineage audit plane (docs/observability.md "Sample lineage &
determinism audit"): chained-order-digest units, recorder reorder/divergence
semantics, digest parity across every pool path and the service fleet,
respawn/attempt invariance, state_dict save/resume continuity, the dry
replay verifier + first-divergence differ (attribution + exit codes), and
the content-fingerprint sampling."""

import json
import os

import numpy as np
import pytest

from petastorm_tpu.reader import make_reader
from petastorm_tpu.telemetry.lineage import (ATTRIBUTION_EXIT_CODES,
                                             EXIT_CONTENT, EXIT_DIVERGED,
                                             EXIT_ERROR, EXIT_OK,
                                             EXIT_QUARANTINE,
                                             EXIT_SCHEDULE_PLAN, EXIT_SEED,
                                             LineagePolicy, LineageRecorder,
                                             canonical_identity,
                                             content_fingerprint,
                                             diff_manifests, fold_digest,
                                             genesis_digest, load_manifest,
                                             main as lineage_main,
                                             manifest_items,
                                             resolve_lineage_policy,
                                             verify_manifest)

from test_common import create_test_dataset

NO_MANIFEST = LineagePolicy(manifest=False)


@pytest.fixture(scope='module')
def dataset(tmp_path_factory):
    url = str(tmp_path_factory.mktemp('lineage') / 'dataset')
    rows = create_test_dataset(url, num_rows=40)
    return {'url': url, 'rows': rows}


def read_digest(url, lineage=NO_MANIFEST, consume='columnar', **kwargs):
    kwargs.setdefault('num_epochs', 1)
    kwargs.setdefault('seed', 7)
    kwargs.setdefault('shuffle_row_groups', True)
    with make_reader(url, lineage=lineage, **kwargs) as reader:
        if consume == 'columnar':
            for _ in reader.iter_columnar(include_empty=True):
                pass
        else:
            for _ in reader:
                pass
        report = reader.diagnostics['lineage']
        return reader.order_digest(), report


# ------------------------------------------------------------------- units

def test_fold_digest_deterministic_and_token_scoped():
    identity = canonical_identity(0, 'a.parquet', 3, None, 0)
    d1 = fold_digest(genesis_digest('tok'), identity, 10)
    d2 = fold_digest(genesis_digest('tok'), identity, 10)
    assert d1 == d2
    assert d1 != fold_digest(genesis_digest('other'), identity, 10)
    assert d1 != fold_digest(genesis_digest('tok'), identity, 11)
    assert d1 != fold_digest(
        genesis_digest('tok'), canonical_identity(0, 'a.parquet', 3, (0, 5), 0),
        10)


def test_canonical_identity_json_safe():
    # numpy ints (fragment enumeration) must not poison the JSON manifest
    identity = canonical_identity(np.int64(1), 'f.parquet', np.int64(2),
                                  (np.int64(0), np.int64(4)), np.int64(1))
    assert identity == [1, 'f.parquet', 2, [0, 4], 1]
    assert json.loads(json.dumps(identity)) == identity
    assert canonical_identity(0, 'f', None, None, 0)[2] is None


def test_resolve_policy_forms(tmp_path):
    assert resolve_lineage_policy(None) is None
    assert resolve_lineage_policy(False) is None
    assert resolve_lineage_policy(True) == LineagePolicy()
    path = str(tmp_path / 'm.jsonl')
    assert resolve_lineage_policy(path).manifest_path == path
    policy = LineagePolicy(fingerprint_every=4)
    assert resolve_lineage_policy(policy) is policy
    with pytest.raises(TypeError):
        resolve_lineage_policy(3.14)
    with pytest.raises(ValueError):
        LineagePolicy(fingerprint_every=-1)
    with pytest.raises(ValueError):
        LineagePolicy(manifest_every=0)


def test_content_fingerprint_array_vs_list_and_corruption():
    a = {'x': np.arange(12, dtype=np.int32).reshape(3, 4)}
    b = {'x': np.arange(12, dtype=np.int32).reshape(3, 4)}
    assert content_fingerprint(a) == content_fingerprint(b)
    b['x'] = b['x'].copy()
    b['x'][1, 2] += 1  # one flipped value must change the CRC
    assert content_fingerprint(a) != content_fingerprint(b)
    # ragged list columns fingerprint cell-by-cell
    ragged = {'y': [np.zeros(2), np.ones(3)]}
    assert content_fingerprint(ragged) == content_fingerprint(
        {'y': [np.zeros(2), np.ones(3)]})
    # object cells fall back to a stable repr
    objs = {'z': np.array(['alpha', 'beta'], dtype=object)}
    assert content_fingerprint(objs) == content_fingerprint(
        {'z': np.array(['alpha', 'beta'], dtype=object)})


def _expect(recorder, epoch, piece, rows_map=None):
    recorder.expect(epoch, piece, 0, 'frag.parquet', piece, None)


def test_recorder_folds_out_of_order_deliveries():
    recorder = LineageRecorder('tok', LineagePolicy(manifest=False))
    for piece in range(4):
        _expect(recorder, 0, piece)
    # deliver out of ventilation order: 2, 0, 3, 1
    recorder.deliver((0, 2, 0), 5)
    assert recorder.report()['items_folded'] == 0  # blocked on piece 0
    recorder.deliver((0, 0, 0), 5)
    assert recorder.report()['items_folded'] == 1  # 2 still waits on 1
    recorder.deliver((0, 3, 0), 5)
    recorder.deliver((0, 1, 0), 5)
    report = recorder.report()
    assert report['items_folded'] == 4 and report['pending_items'] == 0
    # the fold ORDER is ventilation order, independent of delivery order
    expected = genesis_digest('tok')
    for piece in range(4):
        expected = fold_digest(
            expected, canonical_identity(0, 'frag.parquet', piece, None, 0), 5)
    assert recorder.order_digest() == expected.hex()
    assert report['divergence'] == 0


def test_recorder_divergence_unknown_and_duplicate():
    recorder = LineageRecorder('tok', LineagePolicy(manifest=False))
    _expect(recorder, 0, 0)
    _expect(recorder, 0, 1)
    recorder.deliver((0, 9, 0), 5)  # never ventilated
    recorder.deliver((0, 1, 0), 5)  # pending behind piece 0
    recorder.deliver((0, 1, 0), 5)  # duplicate of a pending item
    report = recorder.report()
    assert report['divergence'] == 2
    assert report['last_divergence']['reason'] == 'duplicate_delivery'
    # a re-delivery of an already-FOLDED item surfaces as unexpected (the
    # fold forgets retired keys — bounded memory); still a divergence
    recorder.deliver((0, 0, 0), 5)
    assert recorder.report()['items_folded'] == 2
    recorder.deliver((0, 0, 0), 5)
    assert recorder.report()['divergence'] == 3


def test_recorder_state_roundtrip_mid_stream():
    recorder = LineageRecorder('tok', LineagePolicy(manifest=False))
    for piece in range(5):
        _expect(recorder, 0, piece)
    recorder.deliver((0, 0, 0), 3)
    recorder.deliver((0, 2, 0), 3)  # delivered out of order: pending
    state = recorder.state_dict()
    # JSON roundtrip: checkpoints cross serialization boundaries
    state = json.loads(json.dumps(state))
    resumed = LineageRecorder('tok', LineagePolicy(manifest=False),
                              resume_state=state)
    # pieces 1, 3, 4 re-ventilate (2 was delivered=consumed, never again)
    for piece in (1, 3, 4):
        _expect(resumed, 0, piece)
    for piece in (1, 3, 4):
        resumed.deliver((0, piece, 0), 3)
    baseline = LineageRecorder('tok', LineagePolicy(manifest=False))
    for piece in range(5):
        _expect(baseline, 0, piece)
    for piece in range(5):
        baseline.deliver((0, piece, 0), 3)
    assert resumed.order_digest() == baseline.order_digest()
    assert resumed.report()['divergence'] == 0


def test_recorder_resume_mismatch_is_divergence():
    recorder = LineageRecorder('tok', LineagePolicy(manifest=False))
    _expect(recorder, 0, 0)
    state = recorder.state_dict()
    resumed = LineageRecorder('tok', LineagePolicy(manifest=False),
                              resume_state=state)
    # the resumed ventilator produces a DIFFERENT item where the checkpoint
    # expected piece 0 — that is exactly the bug this plane exists to catch
    resumed.expect(0, 5, 0, 'other.parquet', 5, None)
    assert resumed.report()['divergence'] == 1
    assert resumed.report()['last_divergence']['reason'] == 'resume_mismatch'


# ------------------------------------------------ e2e digest determinism

def test_digest_identical_across_pools(dataset):
    """Acceptance: same seed => byte-identical order_digest() on the dummy,
    thread and process pool paths (completion order differs wildly; the
    ventilation-order fold cancels it)."""
    digests = {}
    for pool in ('dummy', 'thread', 'process'):
        digest, report = read_digest(dataset['url'], reader_pool_type=pool,
                                     workers_count=2, num_epochs=2)
        assert report['divergence'] == 0, (pool, report)
        assert report['pending_items'] == 0
        digests[pool] = digest
    assert len(set(digests.values())) == 1, digests
    # a different seed is a different stream
    other, _ = read_digest(dataset['url'], reader_pool_type='dummy',
                           workers_count=2, num_epochs=2, seed=8)
    assert other != digests['dummy']


def test_digest_identical_on_service_fleet(dataset):
    """Acceptance: a 2-worker service fleet folds the same digest as the
    in-process pools for the same seed."""
    pytest.importorskip('zmq')
    from petastorm_tpu.service.fleet import ServiceFleet
    local, _ = read_digest(dataset['url'], reader_pool_type='dummy')
    with ServiceFleet(workers=2) as fleet:
        served, report = read_digest(dataset['url'],
                                     service_url=fleet.service_url)
    assert served == local
    assert report['divergence'] == 0


def test_digest_row_path_matches_columnar_path(dataset):
    columnar, _ = read_digest(dataset['url'], reader_pool_type='dummy')
    row, _ = read_digest(dataset['url'], reader_pool_type='dummy',
                         consume='rows')
    assert row == columnar


@pytest.mark.faultinject
def test_digest_invariant_under_worker_kill_respawn(dataset):
    """A SIGKILLed worker's in-flight item is re-ventilated by the pool and
    redelivered under a bumped attempt — the identity is attempt-free, so
    the digest must not move."""
    import signal

    from petastorm_tpu.workers.process_pool import ProcessPool
    clean, _ = read_digest(dataset['url'], reader_pool_type='dummy', seed=5)
    pool = ProcessPool(2)
    with make_reader(dataset['url'], reader_pool=pool, seed=5, num_epochs=1,
                     lineage=NO_MANIFEST) as reader:
        stream = reader.iter_columnar(include_empty=True)
        next(stream)
        os.kill(pool._processes[0].pid, signal.SIGKILL)
        for _ in stream:
            pass
        killed = reader.order_digest()
        respawned = pool.diagnostics['workers_respawned']
        divergence = reader.diagnostics['lineage']['divergence']
    assert respawned >= 1
    assert killed == clean
    assert divergence == 0


def test_digest_continuity_across_save_resume(dataset):
    """Acceptance satellite: a mid-epoch state_dict checkpoint + resume
    folds to the exact digest of an uninterrupted run (chain value +
    pending suffix ride the checkpoint)."""
    with make_reader(dataset['url'], reader_pool_type='dummy', seed=11,
                     num_epochs=2, lineage=NO_MANIFEST) as reader:
        for _ in reader:
            pass
        baseline = reader.order_digest()
    first = make_reader(dataset['url'], reader_pool_type='dummy', seed=11,
                        num_epochs=2, lineage=NO_MANIFEST)
    rows_before = 0
    for _ in first:
        rows_before += 1
        if rows_before == 55:  # mid-epoch-2, mid-batch
            break
    state = first.state_dict()
    first.stop()
    first.join()
    assert 'lineage' in state
    state = json.loads(json.dumps(state))  # checkpoints serialize
    with make_reader(dataset['url'], reader_pool_type='dummy', seed=11,
                     num_epochs=2, lineage=NO_MANIFEST,
                     resume_state=state) as reader:
        for _ in reader:
            pass
        resumed = reader.order_digest()
        report = reader.diagnostics['lineage']
    assert resumed == baseline
    assert report['divergence'] == 0


def test_disarmed_reader_is_untouched(dataset):
    with make_reader(dataset['url'], reader_pool_type='dummy',
                     num_epochs=1) as reader:
        for _ in reader.iter_columnar():
            pass
        assert reader.order_digest() is None
        assert 'lineage' not in reader.diagnostics
        assert 'lineage' not in reader.state_dict()
    assert not [name for name in os.listdir(dataset['url'])
                if 'lineage' in name]


def test_batch_reader_and_scrape_gauges(dataset):
    from petastorm_tpu.reader import make_batch_reader
    with pytest.warns(UserWarning):
        reader = make_batch_reader(dataset['url'], lineage=NO_MANIFEST,
                                   num_epochs=1, seed=3)
    with reader:
        for _ in reader.iter_columnar():
            pass
        digest = reader.order_digest()
        snapshot = reader._scrape_snapshot()
    assert digest is not None
    assert snapshot['gauges']['lineage_items_folded'] > 0
    assert snapshot['gauges']['lineage_pending_items'] == 0


def test_loader_step_stamping(dataset):
    from petastorm_tpu.parallel.loader import JaxDataLoader
    with make_reader(dataset['url'], reader_pool_type='dummy', num_epochs=1,
                     schema_fields=['id'], lineage=NO_MANIFEST) as reader:
        loader = JaxDataLoader(reader, batch_size=8, device_put=False,
                               drop_last=False)
        batches = sum(1 for _ in loader)
        assert batches > 0
        assert reader.diagnostics['lineage']['step'] == batches


# ----------------------------------------------------- verify / diff CLI

def record_manifest(url, manifest, seed=29, fingerprint_every=0, **kwargs):
    policy = LineagePolicy(manifest_path=manifest,
                           fingerprint_every=fingerprint_every)
    digest, report = read_digest(url, lineage=policy, seed=seed, **kwargs)
    assert report['divergence'] == 0
    return digest


def test_verify_passes_on_recorded_run(dataset, tmp_path, capsys):
    """Acceptance: ``lineage verify`` re-derives the stream from the header
    (seed + shard config + schedule plan + quarantine ledger) and the store's
    footer metadata — zero data re-read — and confirms the recorded digest."""
    manifest = str(tmp_path / 'run.jsonl')
    digest = record_manifest(dataset['url'], manifest)
    result = verify_manifest(manifest, dataset_url=dataset['url'])
    assert result['ok'], result
    assert result['order_digest'] == digest
    assert result['exit_code'] == EXIT_OK
    # the CLI form (distinct exit codes are the contract scripts consume)
    code = lineage_main(['verify', dataset['url'], '--manifest', manifest,
                         '--json'])
    out = json.loads(capsys.readouterr().out.strip())
    assert code == EXIT_OK and out['ok']


def test_verify_catches_tampered_manifest(dataset, tmp_path):
    manifest = str(tmp_path / 'run.jsonl')
    record_manifest(dataset['url'], manifest)
    lines = open(manifest).read().splitlines()
    tampered = []
    for line in lines:
        record = json.loads(line)
        if record['event'] == 'lineage_manifest' and record['items']:
            record['items'][0][5] = int(record['items'][0][5]) + 1  # rows
        tampered.append(json.dumps(record))
    open(manifest, 'w').write('\n'.join(tampered) + '\n')
    result = verify_manifest(manifest, dataset_url=dataset['url'])
    assert not result['ok'] and result['reason'] == 'chain_mismatch'
    assert result['exit_code'] == EXIT_DIVERGED


def test_verify_catches_reordered_stream(dataset, tmp_path):
    """A manifest whose chain is self-consistent but whose ORDER does not
    derive from the recorded (seed, schedule) replays as divergent."""
    manifest = str(tmp_path / 'run.jsonl')
    record_manifest(dataset['url'], manifest)
    segments = load_manifest(manifest)
    header = segments[-1]['header']
    items = manifest_items(segments[-1])
    items[0], items[1] = items[1], items[0]  # swap the first two deliveries
    # re-chain so only the ORDER is wrong, not the digest arithmetic
    digest = bytes.fromhex(header['genesis'])
    prev = digest
    for row in items:
        digest = fold_digest(digest, row[:5], int(row[5]))
    record = {'event': 'lineage_manifest', 'first_seq': 0, 'step': 0,
              'prev_digest': prev.hex(), 'digest': digest.hex(),
              'items': items}
    with open(manifest, 'w') as f:
        f.write(json.dumps(dict(header, event='lineage_header')) + '\n')
        f.write(json.dumps(record) + '\n')
    result = verify_manifest(manifest, dataset_url=dataset['url'])
    assert not result['ok'] and result['reason'] == 'order_divergence'
    assert result['divergent_step'] == 0


def test_verify_refuses_seedless_shuffle_as_unverifiable(dataset, tmp_path):
    """seed=None shuffles with fresh OS entropy: the order is real but not
    re-derivable — verify must say 'unverifiable' (exit 2), never diagnose
    a false divergence on a healthy run."""
    manifest = str(tmp_path / 'seedless.jsonl')
    policy = LineagePolicy(manifest_path=manifest)
    _digest, report = read_digest(dataset['url'], lineage=policy, seed=None)
    assert report['divergence'] == 0
    result = verify_manifest(manifest, dataset_url=dataset['url'])
    assert not result['ok']
    assert result['reason'] == 'seedless_shuffle'
    assert result['exit_code'] == EXIT_ERROR


def test_interleave_knob_pinned_when_lineage_armed(dataset):
    """The schedule_interleave autotune knob is pinned on lineage-armed
    readers: the manifest header froze the plan, and a mid-run interleave
    flip would make verify diagnose divergence on a legitimate order."""
    from petastorm_tpu.autotune.knobs import build_reader_knobs
    with make_reader(dataset['url'], reader_pool_type='dummy', num_epochs=1,
                     seed=3, shuffle_row_groups=True,
                     cost_schedule=True) as reader:
        unaudited = {knob.knob_id for knob in build_reader_knobs(reader)}
        for _ in reader.iter_columnar():
            pass
    with make_reader(dataset['url'], reader_pool_type='dummy', num_epochs=1,
                     seed=3, shuffle_row_groups=True, cost_schedule=True,
                     lineage=NO_MANIFEST) as reader:
        audited = {knob.knob_id for knob in build_reader_knobs(reader)}
        for _ in reader.iter_columnar():
            pass
    assert 'schedule_interleave' in unaudited
    assert 'schedule_interleave' not in audited
    assert unaudited - audited == {'schedule_interleave'}


def test_verify_headerless_manifest_errors(tmp_path):
    manifest = str(tmp_path / 'orphan.jsonl')
    with open(manifest, 'w') as f:
        f.write(json.dumps({'event': 'lineage_manifest', 'first_seq': 4,
                            'step': 0, 'prev_digest': '00' * 16,
                            'digest': '00' * 16, 'items': []}) + '\n')
    assert verify_manifest(manifest)['exit_code'] == EXIT_ERROR


def test_diff_identical_and_seed_attribution(dataset, tmp_path):
    m_a = str(tmp_path / 'a.jsonl')
    m_b = str(tmp_path / 'b.jsonl')
    m_c = str(tmp_path / 'c.jsonl')
    record_manifest(dataset['url'], m_a, seed=29)
    record_manifest(dataset['url'], m_b, seed=29)
    record_manifest(dataset['url'], m_c, seed=30)
    same = diff_manifests(m_a, m_b)
    assert same['identical'] and same['exit_code'] == EXIT_OK
    diff = diff_manifests(m_a, m_c)
    assert not diff['identical']
    assert diff['attribution'] == 'seed'
    assert diff['exit_code'] == EXIT_SEED
    assert diff['first_divergent_step'] is not None


def test_diff_attributes_ledger_delta_to_schedule_plan(dataset, tmp_path):
    """Acceptance: mutate the cost ledger between two recorded runs (the
    interleave reorders) — ``lineage diff`` reports the first divergent step
    attributed to the schedule plan, with its distinct exit code."""
    from petastorm_tpu.telemetry import tracing
    from petastorm_tpu.telemetry.cost_model import default_ledger_path
    tracing.reset_tracing()
    tracing.set_trace_enabled(True)
    try:
        with make_reader(dataset['url'], workers_count=1, num_epochs=1,
                         shuffle_row_groups=False) as reader:
            for _ in reader.iter_columnar():
                pass
            ledger = reader.cost_ledger()
            token = reader.dataset_token
    finally:
        tracing.set_trace_enabled(False)
        tracing.reset_tracing()
    keys = sorted(ledger._entries)
    total = max(sum(cell['sum_s'] for entry in ledger._entries.values()
                    for cell in entry['stages'].values()), 1e-3)

    def set_heavy(key, scale):
        for other in keys:
            cell = ledger._entries[other]['stages'].setdefault(
                'decode', {'count': 1, 'sum_s': 0.0, 'max_s': 0.0})
            cell['sum_s'] = scale * total if other == key else 1e-4
    ledger_path = default_ledger_path(dataset['url'], token)
    m_a = str(tmp_path / 'a.jsonl')
    m_b = str(tmp_path / 'b.jsonl')
    try:
        set_heavy(keys[0], 50.0)
        ledger.save(ledger_path)
        digest_a = record_manifest(dataset['url'], m_a, cost_schedule=True)
        assert verify_manifest(m_a, dataset_url=dataset['url'])['ok']
        set_heavy(keys[-1], 80.0)  # the ledger delta reorders the interleave
        ledger.save(ledger_path)
        digest_b = record_manifest(dataset['url'], m_b, cost_schedule=True)
    finally:
        os.remove(ledger_path)
    assert digest_a != digest_b
    result = diff_manifests(m_a, m_b)
    assert result['attribution'] == 'schedule_plan', result
    assert result['exit_code'] == EXIT_SCHEDULE_PLAN
    assert result['first_divergent_step'] is not None


def test_diff_attributes_content_corruption(dataset, tmp_path):
    """Same order, different bytes: sampled fingerprints catch what the
    order digest cannot, and diff attributes it to content."""
    m_a = str(tmp_path / 'a.jsonl')
    m_b = str(tmp_path / 'b.jsonl')
    record_manifest(dataset['url'], m_a, fingerprint_every=1)
    record_manifest(dataset['url'], m_b, fingerprint_every=1)
    assert diff_manifests(m_a, m_b)['identical']  # same data, same CRCs
    # simulate silent corruption: one recorded fingerprint flips
    lines = [json.loads(line) for line in open(m_b).read().splitlines()]
    flipped = False
    for record in lines:
        if record['event'] == 'lineage_manifest':
            for row in record['items']:
                if row[6] is not None and not flipped:
                    row[6] = int(row[6]) ^ 0xDEAD
                    flipped = True
    assert flipped
    open(m_b, 'w').write('\n'.join(json.dumps(r) for r in lines) + '\n')
    result = diff_manifests(m_a, m_b)
    assert result['attribution'] == 'content'
    assert result['exit_code'] == EXIT_CONTENT


def test_diff_attributes_quarantine_delta(tmp_path):
    """Header quarantine deltas attribute divergence to the quarantine
    subsystem (a fragment skipped at enumeration shifts every later item)."""
    def write(path, quarantined, items):
        header = {'event': 'lineage_header', 'seed': 1, 'dataset_token': 't',
                  'genesis': genesis_digest('t').hex(),
                  'shuffle_row_groups': False, 'num_epochs': 1,
                  'drop_partitions': 1, 'items': items,
                  'quarantined_fragments': quarantined}
        digest = genesis_digest('t')
        rows = []
        for item in items:
            digest = fold_digest(digest,
                                 canonical_identity(0, item[1], item[2],
                                                    item[3], item[4]), 5)
            rows.append(canonical_identity(0, item[1], item[2], item[3],
                                           item[4]) + [5, None, 0])
        record = {'event': 'lineage_manifest', 'first_seq': 0, 'step': 0,
                  'prev_digest': genesis_digest('t').hex(),
                  'digest': digest.hex(), 'items': rows}
        with open(path, 'w') as f:
            f.write(json.dumps(header) + '\n')
            f.write(json.dumps(record) + '\n')
    m_a = str(tmp_path / 'a.jsonl')
    m_b = str(tmp_path / 'b.jsonl')
    write(m_a, [], [[0, 'f0', 0, None, 0], [1, 'f1', 0, None, 0]])
    write(m_b, ['f0'], [[0, 'f1', 0, None, 0]])
    result = diff_manifests(m_a, m_b)
    assert result['attribution'] == 'quarantine'
    assert result['exit_code'] == EXIT_QUARANTINE


def test_fingerprints_sampled_and_identical_across_pools(dataset, tmp_path):
    """fingerprint_every=1 hashes every piece in the PRODUCING worker; the
    CRCs ride the sidecar and agree across pool paths."""
    m_thread = str(tmp_path / 'thread.jsonl')
    m_process = str(tmp_path / 'process.jsonl')
    record_manifest(dataset['url'], m_thread, fingerprint_every=1,
                    reader_pool_type='thread', workers_count=2)
    record_manifest(dataset['url'], m_process, fingerprint_every=1,
                    reader_pool_type='process', workers_count=2)
    crc_thread = [row[6] for row in manifest_items(load_manifest(m_thread)[-1])]
    crc_process = [row[6]
                   for row in manifest_items(load_manifest(m_process)[-1])]
    assert any(crc is not None for crc in crc_thread)
    assert crc_thread == crc_process
    assert diff_manifests(m_thread, m_process)['identical']


def test_attribution_exit_codes_are_distinct():
    codes = [code for name, code in ATTRIBUTION_EXIT_CODES.items()
             if name != 'unknown']
    assert len(set(codes)) == len(codes)
    assert ATTRIBUTION_EXIT_CODES['identical'] == 0
