"""Epoch-survivable control plane tests (ISSUE 16, docs/service.md
"Restarting with a ledger", docs/robustness.md "Deterministic control-plane
chaos").

Three layers, mirroring tests/test_service.py:

- **ledger units** (no sockets): CRC-framed journal round-trip, epoch bump
  per open, compacting rotation, torn-tail / flipped-byte detection with the
  intact prefix kept, and the discard path;
- **scheduler replay/reshard units** (injectable clock): token-counter
  monotonicity and the delivered-token dedup surviving ``adopt_replay``,
  deterministic elastic resharding of UNDELIVERED work only, and the
  preferred-worker hint (honored when ready, never a stall);
- **end-to-end chaos** (marker ``chaos``): dispatcher SIGKILL mid-epoch with
  a ledger-armed fleet delivering rows-exact with a byte-identical lineage
  digest, a seeded :class:`ChaosSchedule` (dispatcher kill + worker kill)
  with zero duplicates, and a corrupted ledger frame degrading LOUDLY
  (counted CRC drop) while the epoch still completes.
"""
import json
import os

import numpy as np
import pyarrow as pa
import pytest

from petastorm_tpu import make_reader
from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
from petastorm_tpu.etl.dataset_metadata import write_rows
from petastorm_tpu.service.dispatcher import FairShareScheduler
from petastorm_tpu.service.fleet import ServiceFleet
from petastorm_tpu.service.ledger import (LedgerReplay, TokenLedger,
                                          read_frames, replay_journal)
from petastorm_tpu.service.wire import WorkerDescriptor
from petastorm_tpu.telemetry.lineage import (LineagePolicy, diff_manifests,
                                             verify_manifest)
from petastorm_tpu.test_util.chaos import (CHAOS_KINDS, ChaosRule,
                                           ChaosSchedule, run_chaos_epoch)
from petastorm_tpu.unischema import Unischema, UnischemaField

NUM_ROWS = 200
ROWS_PER_FILE = 25  # -> 8 rowgroup work items per epoch
RESPONSE_TIMEOUT_ENV = 'PETASTORM_TPU_SERVICE_RESPONSE_TIMEOUT_S'


def _write_store(root, num_rows=NUM_ROWS):
    schema = Unischema('ChaosProbe', [
        UnischemaField('idx', np.int64, (), ScalarCodec(pa.int64()), False),
        UnischemaField('vec', np.float32, (16,), NdarrayCodec(), False),
    ])
    url = 'file://' + str(root)
    write_rows(url, schema,
               [{'idx': i, 'vec': np.full(16, i, np.float32)}
                for i in range(num_rows)],
               rows_per_file=ROWS_PER_FILE, rowgroup_size_mb=1)
    return url


@pytest.fixture(scope='module')
def chaos_store(tmp_path_factory):
    root = tmp_path_factory.mktemp('chaos') / 'store'
    return {'url': _write_store(root), 'root': root}


# ---------------------------------------------------------------------------
# TokenLedger units (no sockets)
# ---------------------------------------------------------------------------

class TestTokenLedger(object):
    def _path(self, tmp_path):
        return str(tmp_path / 'ledger.bin')

    def test_roundtrip_replay(self, tmp_path):
        path = self._path(tmp_path)
        ledger = TokenLedger(path)
        replay = ledger.open()
        assert replay.result == 'absent'
        assert ledger.epoch == 1
        for token in range(5):
            ledger.append_record('issued', token=token)
        ledger.append_record('client', name='a', host='h', window=8)
        ledger.append_record('setup', setup='s0', digest='d0')
        ledger.append_record('delivered', token=0)
        ledger.append_record('delivered', token=1)
        ledger.append_record('retired', token=0, client='a')
        ledger.append_record('reshard', reason='worker-join')
        ledger.close()

        rep = replay_journal(path)
        assert rep.result == 'ok'
        assert rep.frames_dropped == 0
        assert rep.epoch == 1
        assert rep.next_token == 5
        # retired token 0 left the delivered set; token 1 is still in flight
        # on the client side of the wire and must survive the replay
        assert rep.delivered == {1}
        assert rep.served == {'a': 1}
        assert rep.clients == {'a': {'host': 'h', 'window': 8}}
        assert rep.setups == {'s0': 'd0'}
        assert rep.resharded == 1

    def test_epoch_bumps_on_every_open(self, tmp_path):
        path = self._path(tmp_path)
        for expected in (1, 2, 3):
            ledger = TokenLedger(path)
            ledger.open()
            assert ledger.epoch == expected
            ledger.close()
        assert replay_journal(path).epoch == 3

    def test_failed_and_quarantined_clear_delivered(self, tmp_path):
        path = self._path(tmp_path)
        ledger = TokenLedger(path)
        ledger.open()
        ledger.append_record('delivered', token=7)
        ledger.append_record('delivered', token=8)
        ledger.append_record('failed', token=7)
        ledger.append_record('quarantined', token=8)
        ledger.close()
        assert replay_journal(path).delivered == set()

    def test_rotation_compacts_to_snapshot(self, tmp_path):
        path = self._path(tmp_path)
        ledger = TokenLedger(path, rotate_bytes=1024)
        ledger.open()
        for token in range(400):
            ledger.append_record('issued', token=token)
            ledger.append_record('delivered', token=token)
            ledger.append_record('retired', token=token, client='a')
        ledger.close()
        # 1200 appends compacted away: the journal is bounded by LIVE state
        assert os.path.getsize(path) < 8 * 1024
        rep = replay_journal(path)
        assert rep.result == 'ok'
        assert rep.next_token == 400
        assert rep.delivered == set()
        assert rep.served == {'a': 400}

    def test_flipped_byte_degrades_loudly_keeps_prefix(self, tmp_path):
        path = self._path(tmp_path)
        ledger = TokenLedger(path)
        ledger.open()
        ledger.append_record('issued', token=0)
        ledger.append_record('issued', token=1)
        ledger.close()
        # flip one byte inside the LAST frame: its CRC must catch it while
        # every verified frame before it stays trusted
        size = os.path.getsize(path)
        with open(path, 'r+b') as f:
            f.seek(size - 3)
            byte = f.read(1)
            f.seek(size - 3)
            f.write(bytes([byte[0] ^ 0xFF]))
        rep = replay_journal(path)
        assert rep.result == 'corrupt'
        assert rep.frames_dropped == 1
        assert rep.next_token == 1  # token 0's frame survived; token 1's did not

    def test_torn_tail_counts_as_one_dropped_frame(self, tmp_path):
        path = self._path(tmp_path)
        ledger = TokenLedger(path)
        ledger.open()
        ledger.append_record('issued', token=0)
        ledger.close()
        with open(path, 'r+b') as f:
            f.truncate(os.path.getsize(path) - 3)
        records, dropped = read_frames(path)
        assert dropped == 1
        assert [r['kind'] for r in records] == ['epoch']

    def test_reopen_after_corruption_degrades_then_recovers(self, tmp_path):
        """A corrupt replay is reported, and the NEXT life appends cleanly
        past it — the journal heals at the following rotation, the state
        report stays loud in the meantime."""
        path = self._path(tmp_path)
        ledger = TokenLedger(path)
        ledger.open()
        ledger.append_record('issued', token=9)
        ledger.close()
        with open(path, 'r+b') as f:
            f.truncate(os.path.getsize(path) - 2)
        ledger = TokenLedger(path)
        replay = ledger.open()
        assert replay.result == 'corrupt'
        assert ledger.state()['last_replay'] == 'corrupt'
        assert ledger.state()['frames_dropped'] == 1
        ledger.append_record('issued', token=10)
        ledger.close()

    def test_discard_open_truncates_journal(self, tmp_path):
        path = self._path(tmp_path)
        ledger = TokenLedger(path)
        ledger.open()
        ledger.append_record('issued', token=3)
        ledger.close()
        ledger = TokenLedger(path)
        replay = ledger.open(discard=True)
        ledger.close()
        assert replay.result == 'discarded'
        rep = replay_journal(path)
        # only the fresh epoch record remains; the poisoned history is gone
        assert rep.next_token == 0
        assert rep.records == 1

    def test_append_after_close_is_noop(self, tmp_path):
        ledger = TokenLedger(self._path(tmp_path))
        ledger.open()
        ledger.close()
        ledger.append_record('issued', token=0)  # must not raise
        assert ledger.state()['armed'] is False


# ---------------------------------------------------------------------------
# FairShareScheduler replay + reshard units (injectable clock, no sockets)
# ---------------------------------------------------------------------------

class TestSchedulerReplayAndReshard(object):
    def _scheduler(self, **kwargs):
        self.now = [0.0]
        kwargs.setdefault('clock', lambda: self.now[0])
        return FairShareScheduler(**kwargs)

    @staticmethod
    def _register_worker(sched, key=b'w0', worker_id=0):
        sched.add_worker(key, WorkerDescriptor(worker_id=worker_id, pid=1,
                                               host='h', shm_results=False))
        sched.worker_ready(key)

    def test_adopt_replay_restores_token_monotonicity(self):
        sched = self._scheduler()
        replay = LedgerReplay()
        replay.next_token = 57
        sched.adopt_replay(replay, epoch=3)
        assert sched.ledger_epoch == 3
        sched.add_client(b'A', 'a', 'h')
        token = sched.submit(b'A', b'0', b's', b'blob')
        # a fresh token can never collide with a pre-crash one
        assert token >= 57

    def test_replayed_delivered_token_result_is_dropped(self):
        """A straggler ``w_result`` for a token the LEDGER remembers as
        delivered pre-crash is a duplicate even though no live _TokenState
        holds it — dropped and counted, never forwarded twice."""
        sched = self._scheduler()
        replay = LedgerReplay()
        replay.next_token = 42
        replay.delivered = {41}
        sched.adopt_replay(replay, epoch=2)
        dropped_before = sched.results_dropped
        assert sched.result_route(41) is None
        assert sched.results_dropped == dropped_before + 1

    def _loaded_scheduler(self, submits=6):
        sched = self._scheduler(admission_window=64)
        sched.add_client(b'A', 'a', 'h')
        sched.add_setup(b'A', b's', b'setup')
        tokens = [sched.submit(b'A', b'%d' % i, b's', b'blob')
                  for i in range(submits)]
        assert all(t is not None for t in tokens)
        self._register_worker(sched, b'w0', 0)
        self._register_worker(sched, b'w1', 1)
        return sched, tokens

    def test_reshard_is_deterministic_and_round_robin(self):
        shards = []
        for _ in range(2):
            sched, tokens = self._loaded_scheduler()
            summary = sched.reshard('worker-join')
            assert summary is not None
            assert summary['undelivered'] == len(tokens)
            assert summary['workers'] == 2
            shards.append(dict(sched._preferred_worker))
        # same clients + queues + worker set -> byte-identical placement
        assert shards[0] == shards[1]
        sched, tokens = self._loaded_scheduler()
        sched.reshard('worker-join')
        # ventilation order dealt round-robin across sorted worker ids
        assert [sched._preferred_worker[t] for t in tokens] == \
            [0, 1, 0, 1, 0, 1]

    def test_reshard_moves_only_undelivered_work(self):
        sched, tokens = self._loaded_scheduler()
        assignment = sched.next_assignment()
        assert assignment is not None
        summary = sched.reshard('worker-leave')
        # the in-flight token is NOT re-split — only still-queued work moves
        assert summary['undelivered'] == len(tokens) - 1
        assert assignment.token not in sched._preferred_worker

    def test_next_assignment_honors_reshard_preference(self):
        sched = self._scheduler(admission_window=64)
        sched.add_client(b'A', 'a', 'h')
        sched.add_setup(b'A', b's', b'setup')
        tokens = [sched.submit(b'A', b'%d' % i, b's', b'blob')
                  for i in range(2)]
        # w1 becomes ready FIRST: plain FIFO would hand it the head token
        self._register_worker(sched, b'w1', 1)
        self._register_worker(sched, b'w0', 0)
        sched.reshard('worker-join')
        assignment = sched.next_assignment()
        assert assignment.token == tokens[0]
        # ...but the reshard pinned the head token to sorted worker id 0
        assert assignment.worker_key == b'w0'

    def test_reshard_preference_is_a_hint_never_a_stall(self):
        sched = self._scheduler(admission_window=64)
        sched.add_client(b'A', 'a', 'h')
        sched.add_setup(b'A', b's', b'setup')
        sched.submit(b'A', b'0', b's', b'blob')
        self._register_worker(sched, b'w0', 0)
        self._register_worker(sched, b'w1', 1)
        sched.reshard('worker-join')
        sched.remove_worker(b'w0')  # the preferred worker leaves
        assignment = sched.next_assignment()
        assert assignment is not None
        assert assignment.worker_key == b'w1'

    def test_reshard_returns_none_when_nothing_to_split(self):
        sched = self._scheduler()
        assert sched.reshard('worker-join') is None  # no workers
        self._register_worker(sched)
        assert sched.reshard('worker-join') is None  # no undelivered work

    def test_journal_records_lifecycle(self, tmp_path):
        """The scheduler's journal hooks and the replay agree end to end:
        submit/deliver/retire through a REAL TokenLedger, then replay it."""
        path = str(tmp_path / 'ledger.bin')
        ledger = TokenLedger(path)
        ledger.open()
        sched = self._scheduler(admission_window=64)
        sched.journal = ledger
        sched.add_client(b'A', 'a', 'h')
        sched.add_setup(b'A', b's', b'setup')
        tokens = [sched.submit(b'A', b'%d' % i, b's', b'blob')
                  for i in range(3)]
        self._register_worker(sched)
        assignment = sched.next_assignment()
        assert sched.result_route(assignment.token) is not None
        sched.retire(assignment.token, assignment.attempt)
        ledger.close()

        rep = replay_journal(path)
        assert rep.result == 'ok'
        assert rep.next_token == max(tokens) + 1
        assert rep.delivered == set()  # delivered then retired
        assert rep.served == {'a': 1}
        assert 'a' in rep.clients
        sched2 = self._scheduler()
        sched2.adopt_replay(rep, epoch=rep.epoch + 1)
        sched2.add_client(b'A', 'a', 'h')
        fresh = sched2.submit(b'A', b'9', b's', b'blob')
        assert fresh > max(tokens)


# ---------------------------------------------------------------------------
# ChaosSchedule units
# ---------------------------------------------------------------------------

class TestChaosSchedule(object):
    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError):
            ChaosRule('split_brain')
        assert 'kill_dispatcher' in CHAOS_KINDS

    def test_at_must_be_positive(self):
        with pytest.raises(ValueError):
            ChaosRule('kill_worker', at=0)

    def test_seeded_resolution_is_deterministic(self, tmp_path):
        def resolved(state_dir):
            schedule = ChaosSchedule(state_dir, [
                ChaosRule('kill_dispatcher'),
                ChaosRule('kill_worker'),
            ], seed=7)
            schedule.resolve(horizon=200)
            return [rule.at for rule in schedule.rules]

        first = resolved(str(tmp_path / 'a'))
        second = resolved(str(tmp_path / 'b'))
        assert first == second
        # injuries land mid-epoch: after spin-up, before the natural drain
        assert all(50 <= at < 150 for at in first)

    def test_resolve_requires_a_usable_horizon(self, tmp_path):
        schedule = ChaosSchedule(str(tmp_path), [ChaosRule('kill_worker')],
                                 seed=1)
        with pytest.raises(ValueError):
            schedule.resolve(horizon=3)

    def test_rules_fire_exactly_once(self, tmp_path):
        schedule = ChaosSchedule(str(tmp_path), [
            ChaosRule('kill_dispatcher', at=3),
            ChaosRule('kill_worker', at=10),
        ], seed=0)
        assert schedule.due(2) == []
        fired = schedule.due(5)
        assert [index for index, _ in fired] == [0]
        # the marker file makes the firing once-only for EVERY observer
        assert schedule.due(6) == []
        assert schedule.fired_count() == 1
        rerun = ChaosSchedule(str(tmp_path), schedule.rules, seed=0)
        assert rerun.due(5) == []


# ---------------------------------------------------------------------------
# Checkpoint loader-state JSON guard (parallel/checkpoint.py)
# ---------------------------------------------------------------------------

class TestCheckpointJsonGuard(object):
    def test_json_state_passes(self):
        from petastorm_tpu.parallel.checkpoint import _check_json_roundtrip
        _check_json_roundtrip({'cursor': 3, 'reshard': {'epoch': 2},
                               'order': [1, 2, 3]})

    def test_offending_key_is_named(self):
        from petastorm_tpu.parallel.checkpoint import _check_json_roundtrip
        with pytest.raises(TypeError) as excinfo:
            _check_json_roundtrip({'ledger': {'digest': b'\x00\x01'}})
        message = str(excinfo.value)
        assert 'ledger/digest' in message
        assert 'bytes' in message

    def test_numpy_scalar_is_blamed(self):
        from petastorm_tpu.parallel.checkpoint import _check_json_roundtrip
        with pytest.raises(TypeError) as excinfo:
            _check_json_roundtrip({'rows': np.int64(7)})
        assert 'rows' in str(excinfo.value)


# ---------------------------------------------------------------------------
# End-to-end chaos (real fleet; marker `chaos`)
# ---------------------------------------------------------------------------

def _epoch_ids(dataset_url, service_url, seed, manifest_path=None):
    kwargs = {}
    if manifest_path is not None:
        kwargs['lineage'] = LineagePolicy(manifest_path=manifest_path)
    with make_reader(dataset_url, service_url=service_url, num_epochs=1,
                     seed=seed, shuffle_row_groups=True, **kwargs) as reader:
        return [int(row.idx) for row in reader]


@pytest.mark.chaos
def test_rejoin_after_dispatcher_restart_preserves_lineage(
        chaos_store, tmp_path, monkeypatch):
    """Satellite: live client + workers re-adopt a RESTARTED dispatcher via
    the ledger-epoch handshake — the epoch finishes rows-exact and its
    lineage digest is byte-identical to a same-seed undisturbed run."""
    monkeypatch.setenv(RESPONSE_TIMEOUT_ENV, '2.0')
    seed = 1234
    manifest_a = str(tmp_path / 'baseline.jsonl')
    manifest_b = str(tmp_path / 'restart.jsonl')

    with ServiceFleet(workers=2,
                      cache_dir=str(tmp_path / 'cache-a')) as fleet:
        baseline = _epoch_ids(chaos_store['url'], fleet.service_url, seed,
                              manifest_a)
    assert len(baseline) == NUM_ROWS

    with ServiceFleet(workers=2, cache_dir=str(tmp_path / 'cache-b'),
                      ledger=str(tmp_path / 'ledger.bin')) as fleet:
        ids = []
        policy = LineagePolicy(manifest_path=manifest_b)
        with make_reader(chaos_store['url'], service_url=fleet.service_url,
                         num_epochs=1, seed=seed, shuffle_row_groups=True,
                         lineage=policy) as reader:
            crashed = False
            for row in reader:
                ids.append(int(row.idx))
                if not crashed and len(ids) >= NUM_ROWS // 3:
                    fleet.crash_dispatcher()
                    crashed = True
        assert crashed
        ledger_state = fleet.dispatcher.ledger_state()

    assert len(ids) == NUM_ROWS
    assert sorted(ids) == sorted(baseline)
    # delivery ORDER also survived: the two manifests diff byte-identical
    assert verify_manifest(manifest_b).get('exit_code') == 0
    assert diff_manifests(manifest_a, manifest_b).get('exit_code') == 0
    # the replacement dispatcher is a second ledger life
    assert ledger_state['epoch'] == 2
    assert ledger_state['last_replay'] == 'ok'


@pytest.mark.chaos
def test_seeded_chaos_epoch_rows_exact_zero_duplicates(
        chaos_store, tmp_path, monkeypatch):
    """The harness proper: dispatcher kill AND worker SIGKILL on a seeded
    schedule, every row delivered exactly once."""
    monkeypatch.setenv(RESPONSE_TIMEOUT_ENV, '2.0')
    schedule = ChaosSchedule(str(tmp_path / 'markers'), [
        ChaosRule('kill_dispatcher'),
        ChaosRule('kill_worker', worker_index=0),
    ], seed=7)
    schedule.resolve(horizon=NUM_ROWS)

    ids = []
    with ServiceFleet(workers=2, cache_dir=str(tmp_path / 'cache'),
                      ledger=str(tmp_path / 'ledger.bin')) as fleet:
        with make_reader(chaos_store['url'], service_url=fleet.service_url,
                         num_epochs=1, seed=7,
                         shuffle_row_groups=True) as reader:
            def recording():
                for row in reader:
                    ids.append(int(row.idx))
                    yield row

            report = run_chaos_epoch(recording(), fleet, schedule)

    assert report['rows'] == NUM_ROWS
    assert [f['kind'] for f in report['fired']] == \
        ['kill_dispatcher', 'kill_worker']
    assert schedule.fired_count() == 2
    assert len(ids) == len(set(ids)) == NUM_ROWS  # zero duplicates


@pytest.mark.chaos
def test_corrupt_ledger_frame_degrades_loudly(chaos_store, tmp_path,
                                              monkeypatch):
    """A flipped journal byte before a dispatcher kill: the restart must
    COUNT the dropped frame (doctor WARNING, incident trigger) and still
    finish the epoch rows-exact via replay-from-clients — loud, never
    silently wrong."""
    monkeypatch.setenv(RESPONSE_TIMEOUT_ENV, '2.0')
    schedule = ChaosSchedule(str(tmp_path / 'markers'), [
        ChaosRule('corrupt_ledger', at=40),
        ChaosRule('kill_dispatcher', at=60),
    ], seed=11)

    ids = []
    with ServiceFleet(workers=2, cache_dir=str(tmp_path / 'cache'),
                      ledger=str(tmp_path / 'ledger.bin')) as fleet:
        with make_reader(chaos_store['url'], service_url=fleet.service_url,
                         num_epochs=1, seed=11,
                         shuffle_row_groups=True) as reader:
            def recording():
                for row in reader:
                    ids.append(int(row.idx))
                    yield row

            report = run_chaos_epoch(recording(), fleet, schedule)
        ledger_state = fleet.dispatcher.ledger_state()
        dispatcher_state = fleet.dispatcher.state()

    assert report['rows'] == NUM_ROWS
    assert sorted(ids) == list(range(NUM_ROWS))
    assert ledger_state['last_replay'] == 'corrupt'
    assert ledger_state['frames_dropped'] >= 1
    # the state() snapshot doctor reads (report['ledger']) stays JSON-safe
    payload = json.loads(json.dumps(dispatcher_state))
    assert payload['ledger']['frames_dropped'] >= 1


def test_fetch_service_state_reports_starting_for_half_up_dispatcher():
    """Satellite: a bound-but-silent dispatcher (start-sequence window or a
    wedged pump) yields ``{'state': 'starting'}`` within the timeout instead
    of the unreachable exception — doctor renders a starting service, not a
    dead one."""
    import zmq
    from petastorm_tpu.service.service_client import fetch_service_state
    context = zmq.Context()
    socket = context.socket(zmq.ROUTER)
    try:
        port = socket.bind_to_random_port('tcp://127.0.0.1')
        state = fetch_service_state('tcp://127.0.0.1:{}'.format(port),
                                    timeout_s=1.5)
        assert state['state'] == 'starting'
    finally:
        socket.close(linger=0)
        context.term()
