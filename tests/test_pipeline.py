"""Pipeline parallelism tests (parallel/pipeline.py): the shard_map/ppermute GPipe
schedule must agree exactly with sequentially applying the stages, including grads."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from petastorm_tpu.parallel.pipeline import (make_pipeline, microbatch,
                                             stack_stage_params,
                                             stage_partition_specs,
                                             unstack_stage_params)

N_STAGES = 4
DIM = 8


def stage_fn(params, x):
    return jnp.tanh(x @ params['w'] + params['b'])


def make_stages(seed):
    rng = np.random.RandomState(seed)
    return [{'w': jnp.asarray(rng.randn(DIM, DIM) * 0.5, jnp.float32),
             'b': jnp.asarray(rng.randn(DIM) * 0.1, jnp.float32)}
            for _ in range(N_STAGES)]


def sequential(stages, xs, fn=stage_fn):
    out = xs
    for params in stages:
        out = jax.vmap(lambda mb: fn(params, mb))(out)
    return out


def stage_mesh():
    return Mesh(np.asarray(jax.devices()[:N_STAGES]), ('stage',))


class TestPipelineNumerics(object):
    def test_matches_sequential(self):
        stages = make_stages(0)
        stacked = stack_stage_params(stages)
        xs = jnp.asarray(np.random.RandomState(1).randn(6, 4, DIM), jnp.float32)
        pipe = make_pipeline(stage_fn, stage_mesh())
        ys = jax.jit(pipe)(stacked, xs)
        np.testing.assert_allclose(np.asarray(ys), np.asarray(sequential(stages, xs)),
                                   rtol=1e-6, atol=1e-6)

    def test_single_microbatch_and_many(self):
        stages = make_stages(2)
        stacked = stack_stage_params(stages)
        pipe = jax.jit(make_pipeline(stage_fn, stage_mesh()))
        for n_micro in (1, 2, 8):
            xs = jnp.asarray(np.random.RandomState(n_micro).randn(n_micro, 2, DIM),
                             jnp.float32)
            np.testing.assert_allclose(np.asarray(pipe(stacked, xs)),
                                       np.asarray(sequential(stages, xs)),
                                       rtol=1e-6, atol=1e-6)

    def test_gradients_match_sequential(self):
        stages = make_stages(3)
        stacked = stack_stage_params(stages)
        xs = jnp.asarray(np.random.RandomState(4).randn(4, 2, DIM), jnp.float32)
        target = jnp.ones_like(xs)
        pipe = make_pipeline(stage_fn, stage_mesh())

        def pipe_loss(stacked, xs):
            return jnp.mean((pipe(stacked, xs) - target) ** 2)

        def seq_loss(stacked, xs):
            out = xs
            for i in range(N_STAGES):
                params = unstack_stage_params(stacked, i)
                out = jax.vmap(lambda mb: stage_fn(params, mb))(out)
            return jnp.mean((out - target) ** 2)

        g_pipe = jax.jit(jax.grad(pipe_loss))(stacked, xs)
        g_seq = jax.jit(jax.grad(seq_loss))(stacked, xs)
        for a, b in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_seq)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    def test_stacked_params_shardable(self):
        stacked = stack_stage_params(make_stages(5))
        specs = stage_partition_specs(stacked)
        assert specs['w'] == P('stage', None, None)
        assert specs['b'] == P('stage', None)
        mesh = stage_mesh()
        placed = jax.device_put(
            stacked, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                                  is_leaf=lambda l: isinstance(l, P)))
        # Each device holds exactly its stage's slice.
        shard_shapes = {s.data.shape for s in placed['w'].addressable_shards}
        assert shard_shapes == {(1, DIM, DIM)}


class TestPipelinePlusData(object):
    def test_dp_pp_mesh(self):
        mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(N_STAGES, 2),
                    ('stage', 'data'))
        stages = make_stages(6)
        stacked = stack_stage_params(stages)
        xs = jnp.asarray(np.random.RandomState(7).randn(4, 4, DIM), jnp.float32)
        xs_sharded = jax.device_put(xs, NamedSharding(mesh, P(None, 'data', None)))
        pipe = make_pipeline(stage_fn, mesh, xs_spec=P(None, 'data', None),
                             out_spec=P(None, 'data', None))
        ys = jax.jit(pipe)(stacked, xs_sharded)
        np.testing.assert_allclose(np.asarray(ys), np.asarray(sequential(stages, xs)),
                                   rtol=1e-6, atol=1e-6)

    def test_training_step_decreases_loss(self):
        mesh = stage_mesh()
        stacked = stack_stage_params(make_stages(8))
        xs = jnp.asarray(np.random.RandomState(9).randn(4, 4, DIM), jnp.float32)
        target = jnp.asarray(np.random.RandomState(10).randn(4, 4, DIM) * 0.1,
                             jnp.float32)
        pipe = make_pipeline(stage_fn, mesh)
        optimizer = optax.adam(1e-2)
        opt_state = optimizer.init(stacked)

        @jax.jit
        def step(stacked, opt_state):
            def loss_fn(stacked):
                return jnp.mean((pipe(stacked, xs) - target) ** 2)
            loss, grads = jax.value_and_grad(loss_fn)(stacked)
            updates, opt_state2 = optimizer.update(grads, opt_state, stacked)
            return optax.apply_updates(stacked, updates), opt_state2, loss

        losses = []
        for _ in range(10):
            stacked, opt_state, loss = step(stacked, opt_state)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]


class TestPipelinePlusExpert(object):
    """pp+ep+dp in ONE shard_map program: each pipeline stage is an expert-routed
    FFN using the explicit all-to-all dispatch over the 'expert' axis, with stage
    weights sharded over BOTH 'stage' and 'expert' via params_spec."""

    N_EXPERTS = 4
    D, F = 8, 16
    ROWS = 4          # per microbatch; sharded over data axis 2 -> 2 local rows

    def _stage_params(self, seed):
        rng = np.random.RandomState(seed)
        return {'router': jnp.asarray(rng.randn(self.D, self.N_EXPERTS) * 0.5,
                                      jnp.float32),
                'w1': jnp.asarray(rng.randn(self.N_EXPERTS, self.D, self.F) * 0.3,
                                  jnp.float32),
                'w2': jnp.asarray(rng.randn(self.N_EXPERTS, self.F, self.D) * 0.3,
                                  jnp.float32)}

    def _moe_reference(self, tokens, params):
        """One data shard's routed FFN, the slow way (same math as
        ops.sharded_moe via the shared switch_routing)."""
        from petastorm_tpu.models.moe import _capacity, switch_routing
        probs = jax.nn.softmax(tokens @ params['router'], axis=-1)
        cap = _capacity(tokens.shape[0], self.N_EXPERTS, 1, 8.0)
        dispatch, combine, _, _ = switch_routing(probs, cap, 1)
        slots = jnp.einsum('sxc,sd->xcd', dispatch, tokens)
        h = jax.nn.gelu(jnp.einsum('xcd,xdf->xcf', slots, params['w1']))
        out = jnp.einsum('xcf,xfd->xcd', h, params['w2'])
        return tokens + jnp.einsum('xcd,sxc->sd', out, combine)

    def test_moe_stages_match_reference(self):
        from petastorm_tpu.ops.sharded_moe import sharded_moe_ffn

        mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 2, 2),
                    ('stage', 'expert', 'data'))
        stages = [self._stage_params(20 + s) for s in range(2)]
        stacked = stack_stage_params(stages)
        params_spec = {'router': P('stage', None, None),
                       'w1': P('stage', 'expert', None, None),
                       'w2': P('stage', 'expert', None, None)}

        def stage(params, mb):
            out, _, _ = sharded_moe_ffn(mb, params['router'], params['w1'],
                                        params['w2'], 'expert',
                                        capacity_factor=8.0)
            return mb + out

        pipe = make_pipeline(stage, mesh, xs_spec=P(None, 'data', None),
                             out_spec=P(None, 'data', None),
                             params_spec=params_spec)
        xs = jnp.asarray(np.random.RandomState(30).randn(2, self.ROWS, self.D),
                         jnp.float32)
        got = jax.jit(pipe)(stacked, xs)

        expected = np.empty_like(np.asarray(xs))
        for m in range(xs.shape[0]):
            for half in range(2):                       # data shards of 2 rows
                blk = xs[m, half * 2:(half + 1) * 2]
                for params in stages:
                    blk = self._moe_reference(blk, params)
                expected[m, half * 2:(half + 1) * 2] = np.asarray(blk)
        np.testing.assert_allclose(np.asarray(got), expected, rtol=3e-5, atol=3e-6)
        # Differentiable end to end through BOTH the ppermute schedule and the
        # expert all-to-alls.
        grads = jax.jit(jax.grad(lambda s: jnp.sum(pipe(s, xs) ** 2)))(stacked)
        for leaf in jax.tree.leaves(grads):
            assert np.all(np.isfinite(np.asarray(leaf)))
        assert float(jnp.abs(grads['w1']).sum()) > 0

    def test_bad_params_spec_rejected(self):
        mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 2, 2),
                    ('stage', 'expert', 'data'))
        with pytest.raises(ValueError):
            make_pipeline(stage_fn, mesh,
                          params_spec={'w': P('expert', 'stage'), 'b': P('stage')})
        # None ('replicated') leaves must be rejected, not silently dropped by the
        # tree traversal — they would serve stage 0's weights on every stage.
        with pytest.raises(ValueError):
            make_pipeline(stage_fn, mesh,
                          params_spec={'w': P('stage', None), 'b': None})


class TestPipelineGuards(object):
    def test_missing_axis(self):
        with pytest.raises(ValueError):
            make_pipeline(stage_fn, Mesh(np.asarray(jax.devices()[:4]), ('data',)))

    def test_microbatch_split(self):
        batch = jnp.zeros((8, DIM))
        assert microbatch(batch, 4).shape == (4, 2, DIM)
        with pytest.raises(ValueError):
            microbatch(batch, 3)

    def test_shape_changing_stage_rejected(self):
        def bad_stage(params, x):
            return jnp.concatenate([x, x], axis=-1)
        pipe = make_pipeline(bad_stage, stage_mesh())
        stacked = stack_stage_params(make_stages(11))
        with pytest.raises(ValueError):
            jax.jit(pipe)(stacked, jnp.zeros((2, 2, DIM)))

    def test_empty_stage_list(self):
        with pytest.raises(ValueError):
            stack_stage_params([])


class TestPipelineTensorParallel(object):
    """pp x tp in ONE shard_map (the __graft_entry__ phase-6 pattern): per-stage
    residual MLPs with the hidden dim sharded over a 'model' axis and a psum
    restoring each stage's output — must agree numerically (values AND grads)
    with the dense sequential network."""

    HID = 16

    def _mesh(self):
        return Mesh(np.asarray(jax.devices()[:4]).reshape(2, 2),
                    ('stage', 'model'))

    def _stages(self, seed):
        rng = np.random.RandomState(seed)
        return [{'w1': jnp.asarray(rng.randn(DIM, self.HID) * 0.3, jnp.float32),
                 'w2': jnp.asarray(rng.randn(self.HID, DIM) * 0.3, jnp.float32)}
                for _ in range(2)]

    _specs = {'w1': P('stage', None, 'model'), 'w2': P('stage', 'model', None)}

    @staticmethod
    def _tp_stage_fn(p, mb):
        # local hidden slice; psum over 'model' restores the full MLP output
        h = jax.nn.gelu(mb @ p['w1'])
        return mb + jax.lax.psum(h @ p['w2'], 'model')

    @staticmethod
    def _dense_stage_fn(p, mb):
        return mb + jax.nn.gelu(mb @ p['w1']) @ p['w2']

    def _dense(self, stages, xs):
        return sequential(stages, xs, fn=self._dense_stage_fn)

    def _sharded(self, mesh, stages):
        stacked = stack_stage_params(stages)
        placed = jax.device_put(
            stacked, {k: NamedSharding(mesh, s) for k, s in self._specs.items()})
        pipe = make_pipeline(self._tp_stage_fn, mesh, params_spec=self._specs)
        return placed, pipe

    def test_matches_dense(self):
        mesh = self._mesh()
        stages = self._stages(3)
        placed, pipe = self._sharded(mesh, stages)
        xs = jnp.asarray(np.random.RandomState(4).randn(4, 2, DIM), jnp.float32)
        np.testing.assert_allclose(np.asarray(jax.jit(pipe)(placed, xs)),
                                   np.asarray(self._dense(stages, xs)),
                                   rtol=2e-5, atol=2e-5)

    def test_gradients_match_dense(self):
        mesh = self._mesh()
        stages = self._stages(5)
        placed, pipe = self._sharded(mesh, stages)
        xs = jnp.asarray(np.random.RandomState(6).randn(4, 2, DIM), jnp.float32)

        def pipe_obj(p):
            return jnp.sum(pipe(p, xs) ** 2)

        def dense_obj(p):
            return jnp.sum(self._dense(
                [unstack_stage_params(p, i) for i in range(2)], xs) ** 2)

        got = jax.jit(jax.grad(pipe_obj))(placed)
        want = jax.grad(dense_obj)(stack_stage_params(stages))
        for key in ('w1', 'w2'):
            np.testing.assert_allclose(np.asarray(got[key]),
                                       np.asarray(want[key]),
                                       rtol=5e-5, atol=5e-5)
