"""Subprocess body for the multi-process loader proof (tests/test_multiprocess.py).

Runs as one of N real processes coordinated by ``jax.distributed.initialize`` on the
CPU backend: discovers its shard from the JAX runtime (NOT from explicit kwargs),
reads its shard through JaxDataLoader over a global mesh, and reports everything the
parent needs to prove the sharding contract (served row ids, global batch shapes,
process/device counts) as one JSON file.

Not a test module — invoked by path with:
    python _mp_shard_worker.py <process_id> <num_processes> <coordinator> <url> <out>
"""

import json
import os
import sys


def main():
    process_id = int(sys.argv[1])
    num_processes = int(sys.argv[2])
    coordinator = sys.argv[3]
    dataset_url = sys.argv[4]
    out_path = sys.argv[5]

    os.environ['JAX_PLATFORMS'] = 'cpu'
    os.environ['XLA_FLAGS'] = '--xla_force_host_platform_device_count=2'
    import jax
    jax.config.update('jax_platforms', 'cpu')
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes, process_id=process_id)

    import numpy as np
    from petastorm_tpu import make_reader
    from petastorm_tpu.parallel import JaxDataLoader, make_mesh
    from petastorm_tpu.parallel.mesh import distributed_shard_info

    # The flagship discovery path: shard comes from the initialized JAX runtime.
    cur_shard, shard_count = distributed_shard_info()

    reader = make_reader(dataset_url, cur_shard=cur_shard, shard_count=shard_count,
                         workers_count=1, num_epochs=1, shuffle_row_groups=False)
    mesh = make_mesh(('data',))  # global mesh: every device of every process
    loader = JaxDataLoader(reader, batch_size=4, mesh=mesh, drop_last=False)

    served = []
    global_batch_rows = []
    for batch in loader:
        arr = batch['id']
        global_batch_rows.append(int(arr.shape[0]))
        # This process's slice of the global array: exactly the rows it fed in.
        local = np.concatenate(
            [np.asarray(shard.data) for shard in arr.addressable_shards])
        served.extend(int(v) for v in local)
    reader.stop()
    reader.join()

    with open(out_path, 'w') as f:
        json.dump({
            'process_id': process_id,
            'discovered_shard': [cur_shard, shard_count],
            'process_count': jax.process_count(),
            'global_device_count': len(jax.devices()),
            'local_device_count': len(jax.local_devices()),
            'served': served,
            'global_batch_rows': global_batch_rows,
        }, f)
    jax.distributed.shutdown()


if __name__ == '__main__':
    main()
