"""Legacy petastorm pickle shim tests, incl. the restricted-unpickler security posture."""

import pickle

import pytest

from petastorm_tpu.etl.legacy import depickle_legacy_unischema


def test_malicious_builtin_callable_rejected():
    """A crafted pickle reaching for builtins.eval (or any non-data builtin) must fail —
    a blanket 'builtins' module allowlist would execute it."""
    payload = b"cbuiltins\neval\n(S'1+1'\ntR."
    with pytest.raises(pickle.UnpicklingError, match='forbidden'):
        depickle_legacy_unischema(payload)


def test_malicious_os_system_rejected():
    payload = b"cos\nsystem\n(S'true'\ntR."
    with pytest.raises(pickle.UnpicklingError, match='forbidden'):
        depickle_legacy_unischema(payload)


def test_non_unischema_payload_rejected():
    blob = pickle.dumps({'not': 'a schema'})
    with pytest.raises(pickle.UnpicklingError):
        depickle_legacy_unischema(blob)
