"""PyTorch adapter tests (model: petastorm/tests/test_pytorch_dataloader.py, 333 LoC)."""

import numpy as np
import pytest
import torch

from petastorm_tpu import make_batch_reader, make_reader
from petastorm_tpu.pytorch import BatchedDataLoader, DataLoader, InMemBatchedDataLoader


FIELDS = ['id', 'matrix', 'python_primitive_uint8']


def test_dataloader_batches(synthetic_dataset):
    with make_reader(synthetic_dataset.url, schema_fields=FIELDS,
                     workers_count=2) as reader:
        loader = DataLoader(reader, batch_size=10)
        batches = list(loader)
    assert sum(b['id'].shape[0] for b in batches) == 100
    batch = batches[0]
    assert isinstance(batch['matrix'], torch.Tensor)
    assert batch['matrix'].shape[1:] == (4, 3)


def test_dataloader_values_roundtrip(synthetic_dataset):
    with make_reader(synthetic_dataset.url, schema_fields=FIELDS,
                     workers_count=1) as reader:
        batch = next(iter(DataLoader(reader, batch_size=4)))
    i = int(batch['id'][0])
    source = synthetic_dataset.rows_by_id[i]
    np.testing.assert_array_almost_equal(batch['matrix'][0].numpy(), source['matrix'])


def test_dataloader_shuffling_queue(synthetic_dataset):
    def read(shuffle):
        with make_reader(synthetic_dataset.url, schema_fields=['id'],
                         shuffle_row_groups=False, workers_count=1) as reader:
            loader = DataLoader(reader, batch_size=100,
                                shuffling_queue_capacity=50 if shuffle else 0, seed=1)
            return torch.cat([b['id'] for b in loader]).tolist()
    plain, shuffled = read(False), read(True)
    assert sorted(plain) == sorted(shuffled)
    assert plain != shuffled


def test_dataloader_rejects_strings(synthetic_dataset):
    with make_reader(synthetic_dataset.url, schema_fields=['id', 'sensor_name'],
                     workers_count=1) as reader:
        loader = DataLoader(reader, batch_size=4)
        with pytest.raises(TypeError, match='sensor_name'):
            next(iter(loader))


def test_dataloader_no_concurrent_iteration(synthetic_dataset):
    with make_reader(synthetic_dataset.url, schema_fields=['id'],
                     workers_count=1, num_epochs=None) as reader:
        loader = DataLoader(reader, batch_size=4)
        it = iter(loader)
        next(it)
        with pytest.raises(RuntimeError, match='Concurrent'):
            next(iter(loader))


def test_batched_dataloader(scalar_dataset):
    with make_batch_reader(scalar_dataset.url, schema_fields=['id', 'float64'],
                           workers_count=1) as reader:
        loader = BatchedDataLoader(reader, batch_size=16)
        batches = list(loader)
    assert sum(len(b['id']) for b in batches) == 50
    assert isinstance(batches[0]['float64'], torch.Tensor)


def test_batched_dataloader_requires_batch_reader(synthetic_dataset):
    with make_reader(synthetic_dataset.url, workers_count=1) as reader:
        with pytest.raises(ValueError):
            BatchedDataLoader(reader, batch_size=4)


def test_batched_dataloader_shuffle(scalar_dataset):
    def read(capacity):
        with make_batch_reader(scalar_dataset.url, schema_fields=['id'],
                               shuffle_row_groups=False, workers_count=1) as reader:
            loader = BatchedDataLoader(reader, batch_size=10,
                                       shuffling_queue_capacity=capacity, seed=5)
            return torch.cat([b['id'] for b in loader]).tolist()
    plain, shuffled = read(0), read(40)
    assert sorted(plain) == sorted(shuffled)
    assert plain != shuffled


def test_inmem_loader_epochs(scalar_dataset):
    with make_batch_reader(scalar_dataset.url, schema_fields=['id'],
                           workers_count=1, num_epochs=1) as reader:
        loader = InMemBatchedDataLoader(reader, batch_size=10, num_epochs=3, seed=7)
        batches = list(loader)
    assert len(batches) == 15  # 50 rows / 10 per batch * 3 epochs
    first_epoch = torch.cat([b['id'] for b in batches[:5]]).tolist()
    second_epoch = torch.cat([b['id'] for b in batches[5:10]]).tolist()
    assert sorted(first_epoch) == sorted(second_epoch)
    assert first_epoch != second_epoch  # different seeded permutation per epoch


def test_inmem_loader_capacity(scalar_dataset):
    with make_batch_reader(scalar_dataset.url, schema_fields=['id'],
                           workers_count=1) as reader:
        loader = InMemBatchedDataLoader(reader, batch_size=10, rows_capacity=20,
                                        num_epochs=1)
        total = sum(len(b['id']) for b in loader)
    assert total == 20


def test_weighted_sampling_reader(synthetic_dataset):
    from petastorm_tpu.weighted_sampling_reader import WeightedSamplingReader
    r1 = make_reader(synthetic_dataset.url, schema_fields=['id'], workers_count=1,
                     num_epochs=None)
    r2 = make_reader(synthetic_dataset.url, schema_fields=['id'], workers_count=1,
                     num_epochs=None)
    with WeightedSamplingReader([r1, r2], [0.8, 0.2], seed=0) as mixed:
        rows = [next(mixed) for _ in range(50)]
    assert len(rows) == 50


def test_weighted_sampling_validates_schemas(synthetic_dataset):
    from petastorm_tpu.weighted_sampling_reader import WeightedSamplingReader
    r1 = make_reader(synthetic_dataset.url, schema_fields=['id'], workers_count=1)
    r2 = make_reader(synthetic_dataset.url, schema_fields=['id', 'id2'], workers_count=1)
    try:
        with pytest.raises(ValueError, match='same fields'):
            WeightedSamplingReader([r1, r2], [0.5, 0.5])
    finally:
        for r in (r1, r2):
            r.stop()
            r.join()


def test_decimal_friendly_collate_dicts_and_tuples():
    """Decimal values survive collate into float tensors whether nested in dicts or
    tuples (reference: test_pytorch_dataloader.py:126-152)."""
    import decimal

    import torch

    from petastorm_tpu.pytorch import decimal_friendly_collate
    rows = [{'d': decimal.Decimal('1.5'), 'x': np.int64(1)},
            {'d': decimal.Decimal('2.5'), 'x': np.int64(2)}]
    out = decimal_friendly_collate(rows)
    assert torch.is_tensor(out['d'])
    np.testing.assert_allclose(out['d'].numpy(), [1.5, 2.5])
    tuples = [(decimal.Decimal('0.25'), np.float32(1.0)),
              (decimal.Decimal('0.75'), np.float32(2.0))]
    out_t = decimal_friendly_collate(tuples)
    np.testing.assert_allclose(out_t[0].numpy(), [0.25, 0.75])


def test_dataloader_reiteration_after_exhaustion(synthetic_dataset):
    """iter() works repeatedly on the same loader: each pass re-reads the store
    (reference: test_pytorch_dataloader.py:243-259)."""
    from petastorm_tpu.pytorch import DataLoader
    with make_reader(synthetic_dataset.url, workers_count=1, num_epochs=1,
                     schema_fields=['id'], shuffle_row_groups=False) as reader:
        loader = DataLoader(reader, batch_size=10)
        first = sorted(int(i) for b in loader for i in b['id'])
        second = sorted(int(i) for b in loader for i in b['id'])
    expected = sorted(r['id'] for r in synthetic_dataset.rows)
    assert first == expected
    assert second == expected
