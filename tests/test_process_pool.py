"""Process-pool e2e (kept to a few tests: spawned-interpreter startup is slow on this
1-core box; model: the reference's pytest-forked process-pool pass, unittest.yml:104-108)."""

import numpy as np
import pytest

from petastorm_tpu import make_reader
from petastorm_tpu.transform import TransformSpec


@pytest.mark.slow
def test_process_pool_reads_and_decodes(synthetic_dataset):
    with make_reader(synthetic_dataset.url, reader_pool_type='process',
                     workers_count=2) as reader:
        rows = {row.id: row for row in reader}
    assert len(rows) == len(synthetic_dataset.rows)
    source = synthetic_dataset.rows_by_id[0]
    np.testing.assert_array_equal(rows[0].matrix, source['matrix'])
    np.testing.assert_array_equal(rows[0].image_png, source['image_png'])


@pytest.mark.slow
def test_process_pool_worker_exception_propagates(synthetic_dataset):
    def bad(row):
        raise RuntimeError('cross-process boom')

    with pytest.raises(RuntimeError, match='cross-process boom'):
        with make_reader(synthetic_dataset.url, reader_pool_type='process',
                         workers_count=2, transform_spec=TransformSpec(bad)) as reader:
            list(reader)


@pytest.mark.slow
def test_worker_hard_kill_raises_when_respawn_disabled(synthetic_dataset):
    """With ``max_worker_respawns=0`` a SIGKILL-ed worker mid-read must surface
    WorkerTerminationError promptly (reference failure-detection contract,
    SURVEY.md §5.3) — never hang the consumer, never keep silently serving from the
    survivors. (The default pool instead respawns: see the respawn tests here and in
    test_resilience.py.)"""
    import os
    import signal
    import time

    from petastorm_tpu.workers.process_pool import (ProcessPool,
                                                    WorkerTerminationError)

    pool = ProcessPool(2, max_worker_respawns=0)
    with pytest.raises(WorkerTerminationError):
        with make_reader(synthetic_dataset.url, reader_pool=pool,
                         schema_fields=['id'], num_epochs=None,
                         shuffle_row_groups=False) as reader:
            next(reader)  # pool is up and serving
            for process in pool._processes[:1]:
                os.kill(process.pid, signal.SIGKILL)
            deadline = time.time() + 30
            while time.time() < deadline:
                next(reader)
            pytest.fail('reader kept serving for 30s with a killed worker')


@pytest.mark.slow
def test_worker_hard_kill_respawns_and_completes(synthetic_dataset):
    """Default pool: a killed worker is respawned within the budget, its in-flight
    items are re-ventilated, and the epoch completes with every row served exactly
    once (docs/robustness.md)."""
    import os
    import signal

    from petastorm_tpu.workers.process_pool import ProcessPool

    pool = ProcessPool(2)
    with make_reader(synthetic_dataset.url, reader_pool=pool,
                     schema_fields=['id'], num_epochs=1,
                     shuffle_row_groups=False) as reader:
        ids = [next(reader).id]  # pool is up and serving
        os.kill(pool._processes[0].pid, signal.SIGKILL)
        ids.extend(row.id for row in reader)
        diag = pool.diagnostics
    assert sorted(ids) == sorted(r['id'] for r in synthetic_dataset.rows)
    assert diag['workers_respawned'] == 1
    assert diag['workers_alive'] == 2


@pytest.mark.slow
def test_respawn_budget_exhaustion_raises(synthetic_dataset):
    """Repeated deaths beyond the budget must fail loudly, not respawn forever."""
    import os
    import signal
    import time

    from petastorm_tpu.workers.process_pool import (ProcessPool,
                                                    WorkerTerminationError)

    pool = ProcessPool(2, max_worker_respawns=1)
    with pytest.raises(WorkerTerminationError, match='respawn budget'):
        with make_reader(synthetic_dataset.url, reader_pool=pool,
                         schema_fields=['id'], num_epochs=None,
                         shuffle_row_groups=False) as reader:
            next(reader)
            deadline = time.time() + 60
            while time.time() < deadline:
                for process in pool._processes:
                    if process.poll() is None:
                        os.kill(process.pid, signal.SIGKILL)
                        break
                next(reader)
            pytest.fail('reader kept serving past the respawn budget')
