"""Process-pool e2e (kept to a few tests: spawned-interpreter startup is slow on this
1-core box; model: the reference's pytest-forked process-pool pass, unittest.yml:104-108)."""

import numpy as np
import pytest

from petastorm_tpu import make_reader
from petastorm_tpu.transform import TransformSpec


@pytest.mark.slow
def test_process_pool_reads_and_decodes(synthetic_dataset):
    with make_reader(synthetic_dataset.url, reader_pool_type='process',
                     workers_count=2) as reader:
        rows = {row.id: row for row in reader}
    assert len(rows) == len(synthetic_dataset.rows)
    source = synthetic_dataset.rows_by_id[0]
    np.testing.assert_array_equal(rows[0].matrix, source['matrix'])
    np.testing.assert_array_equal(rows[0].image_png, source['image_png'])


@pytest.mark.slow
def test_process_pool_worker_exception_propagates(synthetic_dataset):
    def bad(row):
        raise RuntimeError('cross-process boom')

    with pytest.raises(RuntimeError, match='cross-process boom'):
        with make_reader(synthetic_dataset.url, reader_pool_type='process',
                         workers_count=2, transform_spec=TransformSpec(bad)) as reader:
            list(reader)


@pytest.mark.slow
def test_worker_hard_kill_raises_instead_of_hanging(synthetic_dataset):
    """SIGKILL-ing a worker mid-read must surface WorkerTerminationError promptly
    (reference failure-detection contract, SURVEY.md §5.3) — never hang the consumer,
    never keep silently serving from the survivors."""
    import os
    import signal
    import time

    from petastorm_tpu.workers.process_pool import (ProcessPool,
                                                    WorkerTerminationError)

    pool = ProcessPool(2)
    with pytest.raises(WorkerTerminationError):
        with make_reader(synthetic_dataset.url, reader_pool=pool,
                         schema_fields=['id'], num_epochs=None,
                         shuffle_row_groups=False) as reader:
            next(reader)  # pool is up and serving
            for process in pool._processes[:1]:
                os.kill(process.pid, signal.SIGKILL)
            deadline = time.time() + 30
            while time.time() < deadline:
                next(reader)
            pytest.fail('reader kept serving for 30s with a killed worker')
