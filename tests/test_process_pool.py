"""Process-pool e2e (kept to a few tests: spawned-interpreter startup is slow on this
1-core box; model: the reference's pytest-forked process-pool pass, unittest.yml:104-108)."""

import numpy as np
import pytest

from petastorm_tpu import make_reader
from petastorm_tpu.transform import TransformSpec


@pytest.mark.slow
def test_process_pool_reads_and_decodes(synthetic_dataset):
    with make_reader(synthetic_dataset.url, reader_pool_type='process',
                     workers_count=2) as reader:
        rows = {row.id: row for row in reader}
    assert len(rows) == len(synthetic_dataset.rows)
    source = synthetic_dataset.rows_by_id[0]
    np.testing.assert_array_equal(rows[0].matrix, source['matrix'])
    np.testing.assert_array_equal(rows[0].image_png, source['image_png'])


@pytest.mark.slow
def test_process_pool_worker_exception_propagates(synthetic_dataset):
    def bad(row):
        raise RuntimeError('cross-process boom')

    with pytest.raises(RuntimeError, match='cross-process boom'):
        with make_reader(synthetic_dataset.url, reader_pool_type='process',
                         workers_count=2, transform_spec=TransformSpec(bad)) as reader:
            list(reader)
