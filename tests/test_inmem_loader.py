"""InMemJaxLoader tests (model: the reference's InMemBatchedDataLoader coverage in
petastorm/tests/test_pytorch_dataloader.py — fill once, seeded epochs, capacity)."""

import jax
import numpy as np
import pytest

from petastorm_tpu import make_reader
from petastorm_tpu.parallel import InMemJaxLoader, make_mesh


def _ids_of(batch):
    return [int(i) for i in np.asarray(batch['id'])]


def test_on_device_epochs_cover_dataset(synthetic_dataset):
    reader = make_reader(synthetic_dataset.url, workers_count=2, num_epochs=1,
                         schema_fields=['id', 'matrix'])
    loader = InMemJaxLoader(reader, batch_size=20, num_epochs=2, seed=4)
    assert loader.num_rows == 100
    assert len(loader) == 5
    epochs = [[], []]
    for i, batch in enumerate(loader):
        assert isinstance(batch['id'], jax.Array)
        assert batch['matrix'].shape[0] == 20
        epochs[i // 5].extend(_ids_of(batch))
    all_ids = sorted(r['id'] for r in synthetic_dataset.rows)
    assert sorted(epochs[0]) == all_ids
    assert sorted(epochs[1]) == all_ids
    # different epoch -> different permutation
    assert epochs[0] != epochs[1]


def test_on_device_seed_reproducible(synthetic_dataset):
    def run():
        # full reproducibility needs a seeded reader too (fill order = rowgroup order)
        reader = make_reader(synthetic_dataset.url, workers_count=1, num_epochs=1,
                             schema_fields=['id'], shuffle_row_groups=False)
        loader = InMemJaxLoader(reader, batch_size=10, num_epochs=1, seed=123)
        return [i for b in loader for i in _ids_of(b)]
    assert run() == run()


def test_rows_capacity_stops_infinite_reader(synthetic_dataset):
    reader = make_reader(synthetic_dataset.url, workers_count=1, num_epochs=None,
                         schema_fields=['id'])
    loader = InMemJaxLoader(reader, batch_size=10, num_epochs=1, rows_capacity=30)
    assert loader.num_rows == 30
    assert sum(len(_ids_of(b)) for b in loader) == 30


def test_infinite_reader_without_capacity_rejected(synthetic_dataset):
    reader = make_reader(synthetic_dataset.url, workers_count=1, num_epochs=None,
                         schema_fields=['id'])
    with pytest.raises(ValueError, match='rows_capacity'):
        InMemJaxLoader(reader, batch_size=10)


def test_mesh_path_shards_batches(synthetic_dataset):
    mesh = make_mesh(('data',))
    reader = make_reader(synthetic_dataset.url, workers_count=1, num_epochs=1,
                         schema_fields=['id', 'matrix'])
    loader = InMemJaxLoader(reader, batch_size=16, num_epochs=1, mesh=mesh, seed=2)
    batches = list(loader)
    assert len(batches) == 100 // 16
    for batch in batches:
        assert batch['id'].sharding.is_fully_addressable
        assert batch['matrix'].shape[0] == 16
    ids = [i for b in batches for i in _ids_of(b)]
    assert len(set(ids)) == len(ids)  # no duplicates within the epoch


def test_drop_last_false_serves_tail(synthetic_dataset):
    reader = make_reader(synthetic_dataset.url, workers_count=1, num_epochs=1,
                         schema_fields=['id'])
    loader = InMemJaxLoader(reader, batch_size=30, num_epochs=1, drop_last=False,
                            device_put=False)
    sizes = [len(b['id']) for b in loader]
    assert sizes == [30, 30, 30, 10]


def test_fill_from_weighted_sampling_reader(synthetic_dataset):
    """Readers without the columnar fast path (WeightedSamplingReader) fill through the
    row-accumulation fallback."""
    from petastorm_tpu.weighted_sampling_reader import WeightedSamplingReader
    r1 = make_reader(synthetic_dataset.url, workers_count=1, num_epochs=1,
                     schema_fields=['id'])
    r2 = make_reader(synthetic_dataset.url, workers_count=1, num_epochs=1,
                     schema_fields=['id'])
    mixed = WeightedSamplingReader([r1, r2], [0.5, 0.5], seed=0)
    loader = InMemJaxLoader(mixed, batch_size=10, num_epochs=1, device_put=False,
                            drop_last=False)
    assert loader.num_rows > 0
    assert sum(len(b['id']) for b in loader) == loader.num_rows


def test_host_only_mode(synthetic_dataset):
    reader = make_reader(synthetic_dataset.url, workers_count=1, num_epochs=1,
                         schema_fields=['id'])
    loader = InMemJaxLoader(reader, batch_size=25, num_epochs=1, device_put=False)
    batch = next(iter(loader))
    assert isinstance(batch['id'], np.ndarray)
