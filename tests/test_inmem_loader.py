"""InMemJaxLoader tests (model: the reference's InMemBatchedDataLoader coverage in
petastorm/tests/test_pytorch_dataloader.py — fill once, seeded epochs, capacity)."""

import jax
import numpy as np
import pytest

from petastorm_tpu import make_reader
from petastorm_tpu.parallel import InMemJaxLoader, make_mesh


def _ids_of(batch):
    return [int(i) for i in np.asarray(batch['id'])]


def test_on_device_epochs_cover_dataset(synthetic_dataset):
    reader = make_reader(synthetic_dataset.url, workers_count=2, num_epochs=1,
                         schema_fields=['id', 'matrix'])
    loader = InMemJaxLoader(reader, batch_size=20, num_epochs=2, seed=4)
    assert loader.num_rows == 100
    assert len(loader) == 5
    epochs = [[], []]
    for i, batch in enumerate(loader):
        assert isinstance(batch['id'], jax.Array)
        assert batch['matrix'].shape[0] == 20
        epochs[i // 5].extend(_ids_of(batch))
    all_ids = sorted(r['id'] for r in synthetic_dataset.rows)
    assert sorted(epochs[0]) == all_ids
    assert sorted(epochs[1]) == all_ids
    # different epoch -> different permutation
    assert epochs[0] != epochs[1]


def test_on_device_seed_reproducible(synthetic_dataset):
    def run():
        # full reproducibility needs a seeded reader too (fill order = rowgroup order)
        reader = make_reader(synthetic_dataset.url, workers_count=1, num_epochs=1,
                             schema_fields=['id'], shuffle_row_groups=False)
        loader = InMemJaxLoader(reader, batch_size=10, num_epochs=1, seed=123)
        return [i for b in loader for i in _ids_of(b)]
    assert run() == run()


def test_rows_capacity_stops_infinite_reader(synthetic_dataset):
    reader = make_reader(synthetic_dataset.url, workers_count=1, num_epochs=None,
                         schema_fields=['id'])
    loader = InMemJaxLoader(reader, batch_size=10, num_epochs=1, rows_capacity=30)
    assert loader.num_rows == 30
    assert sum(len(_ids_of(b)) for b in loader) == 30


def test_infinite_reader_without_capacity_rejected(synthetic_dataset):
    reader = make_reader(synthetic_dataset.url, workers_count=1, num_epochs=None,
                         schema_fields=['id'])
    with pytest.raises(ValueError, match='rows_capacity'):
        InMemJaxLoader(reader, batch_size=10)


def test_mesh_path_shards_batches(synthetic_dataset):
    mesh = make_mesh(('data',))
    reader = make_reader(synthetic_dataset.url, workers_count=1, num_epochs=1,
                         schema_fields=['id', 'matrix'])
    loader = InMemJaxLoader(reader, batch_size=16, num_epochs=1, mesh=mesh, seed=2)
    batches = list(loader)
    assert len(batches) == 100 // 16
    for batch in batches:
        assert batch['id'].sharding.is_fully_addressable
        assert batch['matrix'].shape[0] == 16
    ids = [i for b in batches for i in _ids_of(b)]
    assert len(set(ids)) == len(ids)  # no duplicates within the epoch


def test_drop_last_false_serves_tail(synthetic_dataset):
    reader = make_reader(synthetic_dataset.url, workers_count=1, num_epochs=1,
                         schema_fields=['id'])
    loader = InMemJaxLoader(reader, batch_size=30, num_epochs=1, drop_last=False,
                            device_put=False)
    sizes = [len(b['id']) for b in loader]
    assert sizes == [30, 30, 30, 10]


def test_fill_from_weighted_sampling_reader(synthetic_dataset):
    """Readers without the columnar fast path (WeightedSamplingReader) fill through the
    row-accumulation fallback."""
    from petastorm_tpu.weighted_sampling_reader import WeightedSamplingReader
    r1 = make_reader(synthetic_dataset.url, workers_count=1, num_epochs=1,
                     schema_fields=['id'])
    r2 = make_reader(synthetic_dataset.url, workers_count=1, num_epochs=1,
                     schema_fields=['id'])
    mixed = WeightedSamplingReader([r1, r2], [0.5, 0.5], seed=0)
    loader = InMemJaxLoader(mixed, batch_size=10, num_epochs=1, device_put=False,
                            drop_last=False)
    assert loader.num_rows > 0
    assert sum(len(b['id']) for b in loader) == loader.num_rows


def test_host_only_mode(synthetic_dataset):
    reader = make_reader(synthetic_dataset.url, workers_count=1, num_epochs=1,
                         schema_fields=['id'])
    loader = InMemJaxLoader(reader, batch_size=25, num_epochs=1, device_put=False)
    batch = next(iter(loader))
    assert isinstance(batch['id'], np.ndarray)


class TestScanEpochs:
    """scan_epochs compiles sampling + training into one program per epoch."""

    def _loader(self, synthetic_dataset, batch_size=20, shuffle=True):
        # Deterministic fill order: HBM row order is the fill order, so permutation
        # reproducibility across runs needs a reproducible fill.
        reader = make_reader(synthetic_dataset.url, workers_count=1, num_epochs=1,
                             schema_fields=['id'], shuffle_row_groups=False)
        return InMemJaxLoader(reader, batch_size=batch_size, num_epochs=None,
                              shuffle=shuffle, seed=3)

    def test_each_epoch_covers_dataset_once(self, synthetic_dataset):
        loader = self._loader(synthetic_dataset)

        def step(carry, batch):
            return carry + 1, batch['id']

        steps, aux = loader.scan_epochs(step, 0, num_epochs=2)
        assert int(steps) == 2 * len(loader)
        all_ids = sorted(r['id'] for r in synthetic_dataset.rows)
        epoch0 = sorted(int(i) for i in np.asarray(aux[0]).ravel())
        epoch1 = sorted(int(i) for i in np.asarray(aux[1]).ravel())
        assert epoch0 == all_ids
        assert epoch1 == all_ids
        assert np.asarray(aux[0]).ravel().tolist() != \
            np.asarray(aux[1]).ravel().tolist()  # different permutations

    def test_seeded_order_reproducible(self, synthetic_dataset):
        def run():
            loader = self._loader(synthetic_dataset)
            _, aux = loader.scan_epochs(lambda c, b: (c, b['id']), None, num_epochs=1)
            return np.asarray(aux[0]).ravel().tolist()
        assert run() == run()

    def test_no_shuffle_is_sequential(self, synthetic_dataset):
        loader = self._loader(synthetic_dataset, shuffle=False)
        _, aux = loader.scan_epochs(lambda c, b: (c, b['id']), None, num_epochs=1)
        ids = np.asarray(aux[0]).ravel().tolist()
        assert ids == sorted(ids)

    def test_carry_threads_like_training(self, synthetic_dataset):
        import jax.numpy as jnp
        loader = self._loader(synthetic_dataset)

        def step(carry, batch):
            return carry + jnp.sum(batch['id']), None

        total, _ = loader.scan_epochs(step, 0, num_epochs=1)
        assert int(total) == sum(r['id'] for r in synthetic_dataset.rows)

    def test_shuffle_override_per_call(self, synthetic_dataset):
        # A shuffling loader can still run deterministic sequential epochs (e.g. eval
        # or a compute-floor measurement) via the per-call override.
        loader = self._loader(synthetic_dataset, shuffle=True)
        step = lambda c, b: (c, b['id'])  # noqa: E731
        _, aux_seq = loader.scan_epochs(step, None, num_epochs=1, shuffle=False)
        seq = np.asarray(aux_seq[0]).ravel().tolist()
        assert seq == sorted(seq)
        _, aux_shuf = loader.scan_epochs(step, None, num_epochs=1)
        shuf = np.asarray(aux_shuf[0]).ravel().tolist()
        assert shuf != sorted(shuf)

    def test_consecutive_calls_advance_permutation(self, synthetic_dataset):
        loader = self._loader(synthetic_dataset)
        step = lambda c, b: (c, b['id'])  # noqa: E731
        _, aux_a = loader.scan_epochs(step, None, num_epochs=1)
        _, aux_b = loader.scan_epochs(step, None, num_epochs=1)
        first = np.asarray(aux_a[0]).ravel().tolist()
        second = np.asarray(aux_b[0]).ravel().tolist()
        assert first != second  # continued, not replayed
        _, aux_c = loader.scan_epochs(step, None, num_epochs=1, epoch_offset=0)
        assert np.asarray(aux_c[0]).ravel().tolist() == first  # explicit replay
        # The pinned-offset replay must not clobber the cursor: the next default call
        # serves epoch 2, not a repeat of epoch 1.
        _, aux_d = loader.scan_epochs(step, None, num_epochs=1)
        third = np.asarray(aux_d[0]).ravel().tolist()
        assert third not in (first, second)

    def test_partial_tail_with_drop_last_false_rejected(self, synthetic_dataset):
        reader = make_reader(synthetic_dataset.url, workers_count=1, num_epochs=1,
                             schema_fields=['id'], shuffle_row_groups=False)
        loader = InMemJaxLoader(reader, batch_size=30, drop_last=False)  # 100 % 30 != 0
        with pytest.raises(ValueError, match='partial batch'):
            loader.scan_epochs(lambda c, b: (c, None), 0)

    def test_host_mode_rejected(self, synthetic_dataset):
        reader = make_reader(synthetic_dataset.url, workers_count=1, num_epochs=1,
                             schema_fields=['id'])
        loader = InMemJaxLoader(reader, batch_size=8, device_put=False)
        with pytest.raises(ValueError, match='device_put'):
            loader.scan_epochs(lambda c, b: (c, None), 0)


class TestScanEpochsMesh:
    """Mesh-sharded scan_epochs: dataset blocked across device HBM, shard-local
    per-epoch shuffles, collective-free gathers (beyond-reference: whole-epoch
    compilation now composes with data parallelism)."""

    def _loader(self, synthetic_dataset, batch_size=16, shuffle=True, **kwargs):
        reader = make_reader(synthetic_dataset.url, workers_count=1, num_epochs=1,
                             schema_fields=['id'], shuffle_row_groups=False)
        return InMemJaxLoader(reader, batch_size=batch_size, num_epochs=None,
                              shuffle=shuffle, seed=3, mesh=make_mesh(('data',)),
                              **kwargs)

    def test_each_epoch_covers_usable_rows_once(self, synthetic_dataset):
        # 100 rows over 8 shards -> 12 rows/shard, 96 usable (4 dropped, warned)
        with pytest.warns(UserWarning, match='drops 4 trailing rows'):
            loader = self._loader(synthetic_dataset)
            steps, aux = loader.scan_epochs(lambda c, b: (c + 1, b['id']), 0,
                                            num_epochs=2)
        assert int(steps) == 2 * (96 // 16)
        epoch0 = sorted(int(i) for i in np.asarray(aux[0]).ravel())
        epoch1 = sorted(int(i) for i in np.asarray(aux[1]).ravel())
        assert epoch0 == list(range(96))
        assert epoch1 == list(range(96))
        assert np.asarray(aux[0]).ravel().tolist() != \
            np.asarray(aux[1]).ravel().tolist()

    def test_no_shuffle_batches_interleave_shard_blocks(self, synthetic_dataset):
        loader = self._loader(synthetic_dataset, shuffle=False)
        _, aux = loader.scan_epochs(lambda c, b: (c, b['id']), None, num_epochs=1)
        batches = np.asarray(aux[0])  # (6, 16)
        # batch b rows: [s*12 + b*2, s*12 + b*2 + 1] for each shard s — each shard
        # contributes its own contiguous block, in shard order
        expected0 = [s * 12 + j for s in range(8) for j in (0, 1)]
        assert batches[0].tolist() == expected0

    def test_shard_locality_of_shuffle(self, synthetic_dataset):
        # shard-local shuffle: rows never migrate — every epoch, positions
        # [s*local_bs:(s+1)*local_bs] of each batch hold ids from shard s's block
        loader = self._loader(synthetic_dataset)
        _, aux = loader.scan_epochs(lambda c, b: (c, b['id']), None, num_epochs=1)
        batches = np.asarray(aux[0])  # (6, 16), local_bs = 2
        for s in range(8):
            vals = batches[:, s * 2:(s + 1) * 2].ravel()
            assert all(s * 12 <= v < (s + 1) * 12 for v in vals), (s, vals)

    def test_sharded_train_step_composes(self, synthetic_dataset):
        import jax
        import jax.numpy as jnp
        loader = self._loader(synthetic_dataset)

        def step(carry, batch):
            w = carry
            loss, grad = jax.value_and_grad(
                lambda w: jnp.mean((batch['id'].astype(jnp.float32) * w - 1.0) ** 2))(w)
            return w - 0.001 * grad, loss

        w, aux = loader.scan_epochs(step, jnp.float32(0.5), num_epochs=2)
        assert np.isfinite(float(w))
        assert np.isfinite(np.asarray(aux[0]).sum())

    def test_data_resides_sharded(self, synthetic_dataset):
        from jax.sharding import PartitionSpec
        loader = self._loader(synthetic_dataset)
        loader.scan_epochs(lambda c, b: (c, None), 0, num_epochs=1)
        assert loader._data['id'].sharding.spec == PartitionSpec('data')
        assert loader._data['id'].shape == (8, 12)

    def test_batch_size_not_divisible_rejected(self, synthetic_dataset):
        loader = self._loader(synthetic_dataset, batch_size=10)
        with pytest.raises(ValueError, match='divisible'):
            loader.scan_epochs(lambda c, b: (c, None), 0)
        # validation fired BEFORE the upload: the host copy survives (regression:
        # a post-upload failure would permanently brick the loader)
        assert loader._columns is not None
        assert loader._data is None

    def test_dict_partition_spec_rejected(self, synthetic_dataset):
        from jax.sharding import PartitionSpec
        loader = self._loader(synthetic_dataset,
                              partition_spec={'id': PartitionSpec('data')})
        with pytest.raises(ValueError, match='single-axis'):
            loader.scan_epochs(lambda c, b: (c, None), 0)

    def test_iteration_after_scan_raises(self, synthetic_dataset):
        loader = self._loader(synthetic_dataset)
        loader.scan_epochs(lambda c, b: (c, None), 0, num_epochs=1)
        with pytest.raises(RuntimeError, match='scan_epochs moved the dataset'):
            next(iter(loader))

    def test_seeded_reproducible_across_loaders(self, synthetic_dataset):
        def run():
            loader = self._loader(synthetic_dataset)
            _, aux = loader.scan_epochs(lambda c, b: (c, b['id']), None, num_epochs=1)
            return np.asarray(aux[0]).ravel().tolist()
        assert run() == run()


def test_fill_upload_logged_at_info(synthetic_dataset, caplog):
    import logging
    with caplog.at_level(logging.INFO,
                         logger='petastorm_tpu.parallel.inmem_loader'):
        reader = make_reader(synthetic_dataset.url, workers_count=1,
                             num_epochs=1, schema_fields=['id'])
        loader = InMemJaxLoader(reader, batch_size=4, num_epochs=1)
        list(loader)
    assert 'uploaded' in caplog.text and 'rows' in caplog.text


def test_sharded_fill_upload_logged_at_info(synthetic_dataset, caplog):
    import logging
    mesh = make_mesh(('data',), axis_sizes=(4,),
                     devices=jax.devices()[:4])
    with caplog.at_level(logging.INFO,
                         logger='petastorm_tpu.parallel.inmem_loader'):
        reader = make_reader(synthetic_dataset.url, workers_count=1,
                             num_epochs=1, schema_fields=['id'])
        loader = InMemJaxLoader(reader, batch_size=8, num_epochs=None,
                                mesh=mesh)
        loader.scan_epochs(lambda c, b: (c + b['id'].sum(), None), 0,
                           num_epochs=1)
    assert 'shard-blocked over 4 devices' in caplog.text
