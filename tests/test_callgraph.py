"""Unit tests for the whole-program call graph and the per-function
resource summaries behind pipecheck's interprocedural rules
(petastorm_tpu/analysis/callgraph.py, docs/static-analysis.md).

Fixture modules are built in-memory (ast.parse over literal source) so each
test pins exactly one resolution/summary behavior: cycle-safe memoization,
the unique-name dynamic-dispatch fallback, escape-to-owner accounting,
kill-on-reassign/del, alias release credit, and the finally vs broad-handler
vs narrow-handler release split the lifecycle rule judges on.
"""
import ast
import textwrap
from pathlib import Path

from petastorm_tpu.analysis.callgraph import CallGraph, build_summaries
from petastorm_tpu.analysis.config import default_config
from petastorm_tpu.analysis.core import AnalysisContext, SourceModule


def make_modules(files):
    mods = []
    for name, text in sorted(files.items()):
        text = textwrap.dedent(text)
        mods.append(SourceModule(Path('/proj') / name, name, text,
                                 ast.parse(text)))
    return mods


def make_context(mods):
    ctx = AnalysisContext(default_config(), [Path('/proj')])
    ctx.modules = list(mods)
    return ctx


def graph_of(files):
    mods = make_modules(files)
    return CallGraph.build(mods), mods


def summaries_of(files):
    mods = make_modules(files)
    graph = CallGraph.build(mods)
    return build_summaries(make_context(mods), graph), graph


def tracked_of(summaries, key):
    summary = summaries[key]
    assert summary.tracked, 'no tracked acquisitions in ' + key
    return summary.tracked


# ------------------------------------------------------------- resolution


def test_blocking_chain_through_cycle_terminates_and_finds_sleep():
    graph, _ = graph_of({'cyc.py': '''
        import time

        def a():
            b()

        def b():
            a()
            time.sleep(1)
        '''})
    chain = graph.blocking_chain(graph.functions['cyc.py::a'])
    assert chain is not None
    assert chain[-1] == 'time.sleep()'
    # pure cycle with no blocking call resolves to None, not recursion
    graph2, _ = graph_of({'cyc.py': '''
        def a():
            b()

        def b():
            a()
        '''})
    assert graph2.blocking_chain(graph2.functions['cyc.py::a']) is None


def test_resolve_same_module_function_and_self_method():
    graph, mods = graph_of({'mod.py': '''
        def helper():
            pass

        def caller():
            helper()

        class Box:
            def m(self):
                self.n()

            def n(self):
                pass
        '''})
    caller = graph.functions['mod.py::caller']
    call = caller.node.body[0].value
    assert graph.resolve_call(call, caller).qualname == 'helper'
    method = graph.functions['mod.py::Box.m']
    self_call = method.node.body[0].value
    assert graph.resolve_call(self_call, method).qualname == 'Box.n'


def test_dynamic_dispatch_falls_back_to_unique_name_only():
    # one project-wide definition of .drain() -> resolved across modules
    graph, _ = graph_of({
        'a.py': '''
        class Pump:
            def drain(self):
                pass
        ''',
        'b.py': '''
        def run(pump):
            pump.drain()
        '''})
    run_info = graph.functions['b.py::run']
    call = run_info.node.body[0].value
    assert graph.resolve_call(call, run_info).qualname == 'Pump.drain'
    # two definitions -> ambiguity resolves to None (never guess)
    graph2, _ = graph_of({
        'a.py': '''
        class Pump:
            def drain(self):
                pass

        class Sink:
            def drain(self):
                pass
        ''',
        'b.py': '''
        def run(obj):
            obj.drain()
        '''})
    run2 = graph2.functions['b.py::run']
    assert graph2.resolve_call(run2.node.body[0].value, run2) is None


def test_owner_releases_tracks_direct_alias_and_loop_release():
    graph, mods = graph_of({'owner.py': '''
        class Owner:
            def __init__(self, a, b, c, d):
                self._direct = a
                self._aliased = b
                self._looped_x = c
                self._looped_y = d
                self._never = None

            def close(self):
                self._direct.close()
                sock = self._aliased
                sock.close()
                for item in (self._looped_x, self._looped_y):
                    item.close()
        '''})
    module = mods[0]
    for attr in ('_direct', '_aliased', '_looped_x', '_looped_y'):
        assert graph.owner_releases(module, 'Owner', attr), attr
    assert not graph.owner_releases(module, 'Owner', '_never')


def test_always_raises_transitively_through_helper():
    graph, _ = graph_of({'mod.py': '''
        def _fail(exc):
            raise RuntimeError('wedged') from exc

        def handler(exc):
            _fail(exc)

        def soft(exc):
            return None
        '''})
    assert graph.always_raises_transitively(graph.functions['mod.py::handler'])
    assert not graph.always_raises_transitively(graph.functions['mod.py::soft'])


# -------------------------------------------------------------- summaries


def test_summary_kills_binding_on_reassign_and_del():
    summaries, _ = summaries_of({'mod.py': '''
        from multiprocessing import shared_memory

        def rebind():
            seg = shared_memory.SharedMemory(create=True, size=64)
            seg = shared_memory.SharedMemory(create=True, size=64)
            seg.close()

        def deleted():
            seg = shared_memory.SharedMemory(create=True, size=64)
            del seg
        '''})
    rebind = tracked_of(summaries, 'mod.py::rebind')
    assert rebind[0].killed_line is not None  # first acquisition orphaned
    assert rebind[1].released  # the rebound one is closed
    deleted = tracked_of(summaries, 'mod.py::deleted')
    assert deleted[0].killed_line is not None


def test_summary_credits_release_through_local_alias():
    summaries, _ = summaries_of({'mod.py': '''
        from multiprocessing import shared_memory

        def aliased():
            seg = shared_memory.SharedMemory(create=True, size=64)
            handle = seg
            handle.close()
        '''})
    (tracked,) = tracked_of(summaries, 'mod.py::aliased')
    assert tracked.released


def test_release_position_semantics_finally_vs_handlers():
    summaries, _ = summaries_of({'mod.py': '''
        from multiprocessing import shared_memory

        def in_finally(sink):
            seg = shared_memory.SharedMemory(create=True, size=64)
            try:
                sink.push(seg.buf)
            finally:
                seg.close()

        def broad_handler_only(sink):
            seg = shared_memory.SharedMemory(create=True, size=64)
            try:
                sink.push(seg.buf)
            except Exception:
                seg.close()
                raise

        def narrow_handler_only(sink):
            seg = shared_memory.SharedMemory(create=True, size=64)
            try:
                sink.push(seg.buf)
            except OSError:
                seg.close()
                raise
        '''})
    (fin,) = tracked_of(summaries, 'mod.py::in_finally')
    assert fin.released and fin.release_in_finally
    # broad handler covers the error path but NOT the normal path
    (broad,) = tracked_of(summaries, 'mod.py::broad_handler_only')
    assert broad.release_in_finally and not broad.released
    # a narrow handler earns NO finally credit: error paths of other types
    # escape it, and the risk call before the release stays on record so
    # the lifecycle judge can flag the normal-path-only shape
    (narrow,) = tracked_of(summaries, 'mod.py::narrow_handler_only')
    assert not narrow.release_in_finally
    assert narrow.risk_line is not None


def test_factory_return_propagates_to_call_site():
    summaries, _ = summaries_of({'mod.py': '''
        from multiprocessing import shared_memory

        def fresh():
            seg = shared_memory.SharedMemory(create=True, size=64)
            return seg

        def leaky():
            seg = fresh()

        def tidy():
            seg = fresh()
            seg.close()
        '''})
    assert summaries['mod.py::fresh'].returns_spec is not None
    (factory,) = tracked_of(summaries, 'mod.py::fresh')
    assert factory.returned  # ownership moved out: not a leak in the factory
    (leak,) = tracked_of(summaries, 'mod.py::leaky')
    assert not leak.released and not leak.escaped and not leak.returned
    (ok,) = tracked_of(summaries, 'mod.py::tidy')
    assert ok.released


def test_escape_via_container_literal_argument():
    summaries, _ = summaries_of({'mod.py': '''
        import tempfile

        def handoff(spawner):
            fd, path = tempfile.mkstemp()
            spawner.launch([path, '--flag'])
            import os
            os.close(fd)
        '''})
    tracked = tracked_of(summaries, 'mod.py::handoff')
    assert any(t.escaped for t in tracked)  # the path handed to argv
    assert any(t.released for t in tracked)  # the fd closed
