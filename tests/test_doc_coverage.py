"""Documentation-coverage gate (VERDICT r3 item 8 / missing #4).

The reference gates CI on a sphinx autodoc build (`/root/reference/docs/`,
readthedocs.yml + unittest.yml sphinx step); this repo documents the API by hand in
docs/api.md. This gate keeps that honest and machine-checked, locally and in CI:

- every public module under ``petastorm_tpu`` has a module docstring;
- every public class and function defined in those modules has a docstring;
- docs/api.md mentions every public module (nothing ships undocumented).
"""
import importlib
import inspect
import os
import pkgutil

import pytest

import petastorm_tpu

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Modules whose import requires an optional heavyweight dependency present in the
# image; none are skipped silently — this list is the explicit manifest.
OPTIONAL_IMPORT_MODULES = {
    'petastorm_tpu.tf_utils': 'tensorflow',
    'petastorm_tpu.pytorch': 'torch',
    'petastorm_tpu.spark_utils': 'pyspark',
    'petastorm_tpu.tools.spark_session_cli': 'pyspark',
}


def _walk_public_modules():
    names = []
    for info in pkgutil.walk_packages(petastorm_tpu.__path__,
                                      prefix='petastorm_tpu.'):
        if any(part.startswith('_') for part in info.name.split('.')[1:]):
            continue
        names.append(info.name)
    return sorted(names)


PUBLIC_MODULES = _walk_public_modules()


def _import(name):
    dep = OPTIONAL_IMPORT_MODULES.get(name)
    if dep is not None:
        pytest.importorskip(dep)
    return importlib.import_module(name)


def test_module_manifest_is_nonempty():
    # the walker found the real package, not an empty namespace
    assert len(PUBLIC_MODULES) > 25, PUBLIC_MODULES


@pytest.mark.parametrize('module_name', PUBLIC_MODULES)
def test_module_has_docstring(module_name):
    module = _import(module_name)
    assert (module.__doc__ or '').strip(), \
        '{} has no module docstring'.format(module_name)


@pytest.mark.parametrize('module_name', PUBLIC_MODULES)
def test_public_callables_documented(module_name):
    module = _import(module_name)
    undocumented = []
    for name, obj in vars(module).items():
        if name.startswith('_'):
            continue
        if not (inspect.isclass(obj) or inspect.isfunction(obj)):
            continue
        if getattr(obj, '__module__', None) != module_name:
            continue  # re-export; documented where defined
        if not (inspect.getdoc(obj) or '').strip():
            undocumented.append(name)
    assert not undocumented, \
        '{}: public callables without docstrings: {}'.format(
            module_name, sorted(undocumented))


def test_api_md_mentions_every_public_module():
    with open(os.path.join(REPO_ROOT, 'docs', 'api.md')) as f:
        api_text = f.read()
    missing = []
    for module_name in PUBLIC_MODULES:
        short = module_name.replace('petastorm_tpu.', '')
        if short not in api_text and module_name not in api_text:
            missing.append(module_name)
    assert not missing, \
        'docs/api.md does not mention public modules: {}'.format(missing)
