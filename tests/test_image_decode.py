"""DCT-domain image codec + on-chip decode (SURVEY.md §7.3 decode-as-jax-op variant)."""

import numpy as np
import pytest

import jax.numpy as jnp

from petastorm_tpu import make_reader
from petastorm_tpu.codecs import DctCoefficientsCodec, DctImageCodec, ScalarCodec
from petastorm_tpu.etl.dataset_metadata import write_rows
from petastorm_tpu.ops.image_decode import (dct_decode_image, dct_decode_images_jax,
                                            dct_encode_image)
from petastorm_tpu.unischema import Unischema, UnischemaField


def _psnr(a, b):
    mse = np.mean((a.astype(np.float64) - b.astype(np.float64)) ** 2)
    return 10 * np.log10(255.0 ** 2 / mse) if mse else np.inf


def _assert_images_equal_mod_ties(a, b):
    """Equal up to +-1 on a vanishing fraction of pixels (cross-backend 0.5-rounding)."""
    diff = np.abs(a.astype(np.int32) - b.astype(np.int32))
    assert diff.max() <= 1, 'difference beyond a rounding tie'
    assert np.count_nonzero(diff) <= max(1, a.size // 1000)


def _test_image(h, w, c=3, seed=0):
    """Smooth structured image (random noise is the DCT's worst case and not
    representative of photos)."""
    rng = np.random.RandomState(seed)
    yy, xx = np.mgrid[0:h, 0:w]
    chans = []
    for i in range(c):
        base = (128 + 100 * np.sin(xx / (8.0 + 3 * i)) * np.cos(yy / (11.0 + 2 * i))
                + rng.randn(h, w) * 6)
        chans.append(base)
    img = np.stack(chans, axis=-1) if c > 1 else chans[0][..., None]
    return np.clip(img, 0, 255).astype(np.uint8) if c > 1 else \
        np.clip(img[..., 0], 0, 255).astype(np.uint8)


class TestDctTransform:
    @pytest.mark.parametrize('hw', [(64, 64), (60, 50), (17, 33)])
    def test_roundtrip_rgb_psnr(self, hw):
        img = _test_image(*hw)
        coeffs = dct_encode_image(img, quality=90)
        out = dct_decode_image(coeffs, quality=90, orig_hw=hw)
        assert out.shape == img.shape and out.dtype == np.uint8
        assert _psnr(img, out) > 30, 'quality-90 DCT round trip must stay high-fidelity'

    def test_roundtrip_grayscale(self):
        img = _test_image(40, 48, c=1)
        assert img.ndim == 2
        coeffs = dct_encode_image(img, quality=85)
        out = dct_decode_image(coeffs, quality=85, orig_hw=(40, 48))
        assert out.shape == img.shape
        assert _psnr(img, out) > 33

    def test_quality_tradeoff(self):
        img = _test_image(64, 64)
        high = dct_decode_image(dct_encode_image(img, 95), 95, (64, 64))
        low = dct_decode_image(dct_encode_image(img, 20), 20, (64, 64))
        assert _psnr(img, high) > _psnr(img, low)
        # low quality quantizes harder -> more zeros -> compresses smaller
        assert (np.count_nonzero(dct_encode_image(img, 20))
                < np.count_nonzero(dct_encode_image(img, 95)))

    def test_device_decode_matches_host(self):
        """The jitted decode must reproduce the host mirror to within rounding ties
        (float associativity differs between numpy and XLA; a 0.5-boundary pixel may
        round the other way) for /8 shapes."""
        imgs = np.stack([_test_image(64, 64, seed=s) for s in range(3)])
        coeffs = np.stack([dct_encode_image(im, 80) for im in imgs])
        on_host = np.stack([dct_decode_image(c, 80) for c in coeffs])
        on_device = np.asarray(dct_decode_images_jax(jnp.asarray(coeffs), quality=80))
        _assert_images_equal_mod_ties(on_host, on_device)

    def test_encode_rejects_bad_input(self):
        with pytest.raises(ValueError, match='uint8'):
            dct_encode_image(np.zeros((8, 8), np.float32))
        with pytest.raises(ValueError, match='channels'):
            dct_encode_image(np.zeros((8, 8, 4), np.uint8))


SCHEMA = Unischema('DctStore', [
    UnischemaField('id', np.int64, (), ScalarCodec(), False),
    UnischemaField('image', np.uint8, (64, 64, 3), DctImageCodec(quality=90), False),
])


@pytest.fixture(scope='module')
def dct_dataset(tmp_path_factory):
    url = str(tmp_path_factory.mktemp('dct') / 'ds')
    rows = [{'id': i, 'image': _test_image(64, 64, seed=i)} for i in range(12)]
    write_rows(url, SCHEMA, rows, rows_per_file=6, rowgroup_size_mb=64)
    return url, rows


class TestDctCodecEndToEnd:
    def test_host_decode_path(self, dct_dataset):
        url, rows = dct_dataset
        with make_reader(url, workers_count=1, shuffle_row_groups=False) as reader:
            decoded = {row.id: row.image for row in reader}
        assert len(decoded) == 12
        for row in rows:
            assert decoded[row['id']].shape == (64, 64, 3)
            assert _psnr(row['image'], decoded[row['id']]) > 30

    def test_field_override_ships_coefficients_and_decodes_on_device(self, dct_dataset):
        url, rows = dct_dataset
        override = UnischemaField('image', np.int16, (8, 8, 8, 8, 3),
                                  DctCoefficientsCodec(quality=90), False)
        with make_reader(url, workers_count=1, shuffle_row_groups=False,
                         field_overrides=[override]) as reader:
            got = {row.id: row.image for row in reader}
        assert got[0].dtype == np.int16 and got[0].shape == (8, 8, 8, 8, 3)
        # device decode of shipped coefficients == host codec decode
        ids = sorted(got)
        coeffs = jnp.asarray(np.stack([got[i] for i in ids]))
        on_device = np.asarray(dct_decode_images_jax(coeffs, quality=90))
        with make_reader(url, workers_count=1, shuffle_row_groups=False) as reader:
            on_host = {row.id: row.image for row in reader}
        for pos, i in enumerate(ids):
            _assert_images_equal_mod_ties(on_device[pos], on_host[i])

    def test_schema_json_roundtrip(self, dct_dataset):
        url, _ = dct_dataset
        from petastorm_tpu.etl.dataset_metadata import get_schema, open_dataset
        schema = get_schema(open_dataset(url))
        codec = schema.fields['image'].codec
        assert isinstance(codec, DctImageCodec)
        assert codec.quality == 90

    def test_field_overrides_unknown_name_rejected(self, dct_dataset):
        url, _ = dct_dataset
        bad = UnischemaField('nope', np.int16, (), ScalarCodec(), False)
        with pytest.raises(ValueError, match='nope'):
            make_reader(url, field_overrides=[bad])

    def test_field_override_has_own_cache_identity(self, dct_dataset, tmp_path):
        """A host-decode read and a coefficients-override read sharing one disk cache
        must not serve each other's entries (the cached value is post-decode)."""
        url, _ = dct_dataset
        cache_kwargs = dict(cache_type='local-disk', cache_location=str(tmp_path / 'c'),
                            cache_size_limit=1 << 30, workers_count=1,
                            shuffle_row_groups=False)
        with make_reader(url, **cache_kwargs) as reader:
            host_row = next(reader)
        assert host_row.image.dtype == np.uint8
        override = UnischemaField('image', np.int16, (8, 8, 8, 8, 3),
                                  DctCoefficientsCodec(quality=90), False)
        with make_reader(url, field_overrides=[override], **cache_kwargs) as reader:
            coeff_row = next(reader)
        assert coeff_row.image.dtype == np.int16
        assert coeff_row.image.shape == (8, 8, 8, 8, 3)

    def test_storage_size_is_compressed(self, dct_dataset):
        """DCT blob (pre page-compression) stays in the ballpark of the raw image;
        the many zero coefficients are what parquet's page codec then squeezes."""
        img = _test_image(64, 64)
        field = SCHEMA.fields['image']
        blob = DctImageCodec(quality=50).encode(field, img)
        assert len(blob) <= img.nbytes * 2 + 256
        nonzero = np.count_nonzero(dct_encode_image(img, quality=50))
        assert nonzero < img.size // 3  # sparse: page compression has leverage