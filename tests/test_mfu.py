"""MFU accounting (benchmark/mfu.py): analytic FLOP formulas, XLA cost analysis,
peak-FLOPs detection honesty on CPU (VERDICT r3 item 2)."""
import jax
import jax.numpy as jnp
import pytest

from petastorm_tpu.benchmark.mfu import (PEAK_BF16_FLOPS, chip_generation,
                                         mfu_fields,
                                         moe_transformer_train_flops_per_step,
                                         peak_flops,
                                         transformer_train_flops_per_step,
                                         xla_cost_flops)


class TestAnalyticFormulas:
    def test_transformer_hand_computed_tiny_config(self):
        # B=1, T=2, V=4, E=2, L=1, mlp_mult=4, causal:
        # dense = (8 + 16) * 4 * 1 = 96 per token
        # attn  = 2 * 2 * 2 * 1 = 8 per token
        # unembed = 2 * 2 * 4 = 16 per token
        # fwd = 1 * 2 * (96 + 8 + 16) = 240 ; train = 3x = 720
        assert transformer_train_flops_per_step(
            1, 2, vocab=4, embed=2, layers=1) == 720

    def test_transformer_scales_linearly_in_batch_and_layers_quadratic_in_t(self):
        base = transformer_train_flops_per_step(2, 128, 256, 64, 2)
        assert transformer_train_flops_per_step(4, 128, 256, 64, 2) == 2 * base
        # attention term is quadratic in T, dense terms linear: doubling T more
        # than doubles the total
        assert transformer_train_flops_per_step(2, 256, 256, 64, 2) > 2 * base

    def test_non_causal_attention_doubles_the_attn_term(self):
        causal = transformer_train_flops_per_step(1, 64, 16, 8, 1, causal=True)
        full = transformer_train_flops_per_step(1, 64, 16, 8, 1, causal=False)
        # delta is exactly the attention term: 3 * B*T * 2*T*E
        assert full - causal == 3 * 64 * 2 * 64 * 8

    def test_moe_every_layer_selected_one_matches_dense_plus_router(self):
        # num_selected=1, hidden_mult=4: expert MLP FLOPs == dense MLP FLOPs, so
        # the only delta vs TransformerLM is the router projection.
        dense = transformer_train_flops_per_step(2, 32, 64, 16, 2)
        moe = moe_transformer_train_flops_per_step(
            2, 32, 64, 16, 2, num_experts=8, num_selected=1, moe_every=1)
        router = 3 * 2 * 32 * 2 * (2 * 16 * 8)  # 3x fwd * B*T * L_moe * 2*E*n_exp
        assert moe - dense == router

    def test_moe_every_2_mixes_dense_and_moe_layers(self):
        all_moe = moe_transformer_train_flops_per_step(
            1, 16, 32, 8, 4, num_experts=4, moe_every=1)
        half_moe = moe_transformer_train_flops_per_step(
            1, 16, 32, 8, 4, num_experts=4, moe_every=2)
        dense = transformer_train_flops_per_step(1, 16, 32, 8, 4)
        assert dense < half_moe < all_moe

    def test_moe_num_selected_scales_expert_compute(self):
        one = moe_transformer_train_flops_per_step(
            1, 16, 32, 8, 1, num_experts=4, num_selected=1)
        two = moe_transformer_train_flops_per_step(
            1, 16, 32, 8, 1, num_experts=4, num_selected=2)
        assert two > one


class TestPeakDetection:
    def test_cpu_backend_reports_no_generation(self):
        # The suite runs with JAX_PLATFORMS=cpu; PALLAS_AXON_TPU_GEN may still be
        # set in the env — a CPU run must NEVER pick it up (it would fabricate a
        # TPU MFU for a CPU fallback).
        assert jax.devices()[0].platform == 'cpu'
        assert chip_generation() is None
        assert peak_flops() is None

    def test_explicit_generation_lookup(self):
        assert peak_flops('v5e') == 197e12
        assert peak_flops('V5E') == 197e12
        assert peak_flops('v5p') == 459e12
        assert peak_flops('made-up-chip') is None

    def test_peak_table_is_plausible(self):
        assert PEAK_BF16_FLOPS['v4'] < PEAK_BF16_FLOPS['v5p']
        assert PEAK_BF16_FLOPS['v5e'] < PEAK_BF16_FLOPS['v6e']


class TestMfuFields:
    def test_no_flops_yields_empty(self):
        assert mfu_fields('x', None, 10, 1.0) == {}
        assert mfu_fields('x', 0, 10, 1.0) == {}
        assert mfu_fields('x', 1e9, 10, 0.0) == {}

    def test_tflops_reported_without_mfu_on_cpu(self):
        fields = mfu_fields('flash_train', 1e12, steps=10, elapsed_s=2.0)
        assert fields['flash_train_model_tflops_per_sec'] == 5.0
        assert 'flash_train_mfu' not in fields  # no fabricated MFU on CPU

    def test_mfu_with_explicit_generation(self):
        fields = mfu_fields('moe_train', 197e12, steps=1, elapsed_s=2.0,
                            generation='v5e')
        assert fields['moe_train_mfu'] == pytest.approx(0.5)
        assert fields['mfu_peak_bf16_tflops'] == 197.0


class TestXlaCostFlops:
    def test_matmul_flops_counted(self):
        f = jax.jit(lambda a, b: a @ b)
        a = jnp.zeros((64, 64), jnp.float32)
        flops = xla_cost_flops(f, a, a)
        if flops is None:
            pytest.skip('cost analysis not exposed on this backend')
        # 64^3 MACs = 2*64^3 = 524288 FLOPs; allow backend fusion slack
        assert flops >= 2 * 64 ** 3 * 0.5

    def test_bad_program_returns_none(self):
        f = jax.jit(lambda a: a)

        class NotAnArray:
            pass

        assert xla_cost_flops(f, NotAnArray()) is None
