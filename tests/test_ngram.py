"""NGram tests (model: petastorm/tests/test_ngram_end_to_end.py, 630 LoC)."""

import numpy as np
import pytest

from petastorm_tpu import make_reader
from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
from petastorm_tpu.etl.dataset_metadata import write_rows
from petastorm_tpu.ngram import NGram
from petastorm_tpu.unischema import Unischema, UnischemaField

SeqSchema = Unischema('SeqSchema', [
    UnischemaField('ts', np.int64, (), ScalarCodec(), False),
    UnischemaField('value', np.float32, (2,), NdarrayCodec(), False),
    UnischemaField('label', np.int32, (), ScalarCodec(), False),
])


def _seq_rows(timestamps):
    return [{'ts': int(t), 'value': np.array([t, t * 2], dtype=np.float32),
             'label': np.int32(t % 3)} for t in timestamps]


@pytest.fixture(scope='module')
def seq_dataset(tmp_path_factory):
    url = str(tmp_path_factory.mktemp('seq') / 'ds')
    # one file, one rowgroup: windows can span the full range
    write_rows(url, SeqSchema, _seq_rows(range(20)), rows_per_file=20,
               rowgroup_size_mb=64)
    return url


class TestFormNgram:
    def test_docstring_example(self):
        """The reference's worked example (ngram.py:60-85): threshold 4, ids
        0,3,8,10,11,20,30 -> windows (0,3),(8,10),(10,11)."""
        ngram = NGram({-1: ['.*'], 0: ['.*']}, delta_threshold=4, timestamp_field='ts')
        ngram.resolve_regex_field_names(SeqSchema)
        rows = [{'ts': t, 'value': None, 'label': 0} for t in [0, 3, 8, 10, 11, 20, 30]]
        windows = ngram.form_ngram(rows)
        pairs = [(w[-1]['ts'], w[0]['ts']) for w in windows]
        assert pairs == [(0, 3), (8, 10), (10, 11)]

    def test_no_overlap(self):
        ngram = NGram({0: ['ts'], 1: ['ts']}, delta_threshold=100, timestamp_field='ts',
                      timestamp_overlap=False)
        ngram.resolve_regex_field_names(SeqSchema)
        rows = [{'ts': t, 'value': None, 'label': 0} for t in range(6)]
        windows = ngram.form_ngram(rows)
        starts = [w[0]['ts'] for w in windows]
        assert starts == [0, 2, 4]

    def test_unsorted_raises(self):
        ngram = NGram({0: ['ts'], 1: ['ts']}, delta_threshold=5, timestamp_field='ts')
        ngram.resolve_regex_field_names(SeqSchema)
        rows = [{'ts': t, 'value': None, 'label': 0} for t in [3, 1, 2]]
        with pytest.raises(NotImplementedError):
            ngram.form_ngram(rows)

    def test_length(self):
        assert NGram({-2: ['a'], 0: ['a']}, 1, 'ts').length == 3
        assert NGram({0: ['a'], 1: ['a']}, 1, 'ts').length == 2

    def test_validation(self):
        with pytest.raises(ValueError):
            NGram({}, 1, 'ts')
        with pytest.raises(ValueError):
            NGram({'a': ['x']}, 1, 'ts')


class TestNgramEndToEnd:
    def test_consecutive_windows(self, seq_dataset):
        ngram = NGram({0: ['ts', 'value'], 1: ['ts', 'label']}, delta_threshold=1,
                      timestamp_field='ts')
        with make_reader(seq_dataset, schema_fields=ngram, workers_count=1,
                         shuffle_row_groups=False) as reader:
            windows = list(reader)
        assert len(windows) == 19
        first = windows[0]
        assert set(first.keys()) == {0, 1}
        assert first[1].ts == first[0].ts + 1
        # per-timestep field subsets
        assert set(first[0]._fields) == {'ts', 'value'}
        assert set(first[1]._fields) == {'ts', 'label'}
        np.testing.assert_array_almost_equal(
            first[0].value, [first[0].ts, first[0].ts * 2])

    def test_per_timestep_schema(self, seq_dataset):
        ngram = NGram({0: ['value'], 1: ['label']}, delta_threshold=2,
                      timestamp_field='ts')
        with make_reader(seq_dataset, schema_fields=ngram, workers_count=1) as reader:
            w = next(reader)
        assert 'value' in w[0]._fields and 'label' in w[1]._fields

    def test_ngram_with_batch_reader_rejected(self, seq_dataset):
        from petastorm_tpu import make_batch_reader
        ngram = NGram({0: ['ts']}, 1, 'ts')
        with pytest.raises(ValueError):
            with pytest.warns(UserWarning):
                make_batch_reader(seq_dataset, schema_fields=ngram)

    def test_ngram_shuffle_drop_partitions(self, seq_dataset):
        ngram = NGram({0: ['ts'], 1: ['ts']}, delta_threshold=1, timestamp_field='ts')
        with make_reader(seq_dataset, schema_fields=ngram, workers_count=1,
                         shuffle_row_drop_partitions=2,
                         shuffle_row_groups=False) as reader:
            windows = list(reader)
        # carry-over rows preserve boundary windows: all 19 consecutive pairs survive
        starts = sorted(w[0].ts for w in windows)
        assert len(starts) == 19

    def test_ngram_epochs(self, seq_dataset):
        ngram = NGram({0: ['ts'], 1: ['ts']}, delta_threshold=1, timestamp_field='ts')
        with make_reader(seq_dataset, schema_fields=ngram, workers_count=1,
                         num_epochs=2, shuffle_row_groups=False) as reader:
            windows = list(reader)
        assert len(windows) == 38

    def test_ngram_delta_threshold_end_to_end(self, tmp_path):
        """Gapped timestamps through the full reader (model: reference's
        test_ngram_delta_threshold over dataset 0,3,8,10,11,20,23)."""
        url = str(tmp_path / 'gaps')
        write_rows(url, SeqSchema, _seq_rows([0, 3, 8, 10, 11, 20, 23]),
                   rows_per_file=7, rowgroup_size_mb=64)
        ngram = NGram({0: ['ts', 'value'], 1: ['ts', 'label']}, delta_threshold=4,
                      timestamp_field='ts')
        with make_reader(url, schema_fields=ngram, workers_count=1,
                         shuffle_row_groups=False) as reader:
            pairs = sorted((w[0].ts, w[1].ts) for w in reader)
        assert pairs == [(0, 3), (8, 10), (10, 11), (20, 23)]

    def test_ngram_delta_small_threshold_no_windows(self, tmp_path):
        """Timestamps spaced wider than the threshold yield no windows at all (model:
        reference's test_ngram_delta_small_threshold)."""
        url = str(tmp_path / 'sparse')
        write_rows(url, SeqSchema, _seq_rows(range(0, 100, 5)), rows_per_file=20,
                   rowgroup_size_mb=64)
        ngram = NGram({0: ['ts'], 1: ['ts']}, delta_threshold=1, timestamp_field='ts')
        with make_reader(url, schema_fields=ngram, workers_count=1) as reader:
            assert list(reader) == []

    def test_ngram_length_1(self, seq_dataset):
        """A one-timestep NGram degenerates to per-row reads wrapped in {0: row}
        (model: reference's test_ngram_length_1)."""
        ngram = NGram({0: ['ts', 'value']}, delta_threshold=10, timestamp_field='ts')
        with make_reader(seq_dataset, schema_fields=ngram, workers_count=1,
                         shuffle_row_groups=False) as reader:
            windows = list(reader)
        assert len(windows) == 20
        assert sorted(w[0].ts for w in windows) == list(range(20))

    def test_ngram_regex_fields_end_to_end(self, seq_dataset):
        """Regex patterns resolve per timestep against the schema (model: reference's
        test_ngram_with_regex_fields)."""
        ngram = NGram({0: ['^ts$', 'val.*'], 1: ['^(ts|label)$']}, delta_threshold=1,
                      timestamp_field='ts')
        with make_reader(seq_dataset, schema_fields=ngram, workers_count=1,
                         shuffle_row_groups=False) as reader:
            w = next(reader)
        assert set(w[0]._fields) == {'ts', 'value'}
        assert set(w[1]._fields) == {'ts', 'label'}

    def test_ngram_no_overlap_end_to_end(self, seq_dataset):
        """timestamp_overlap=False tiles the sequence into disjoint windows through the
        full reader path."""
        ngram = NGram({0: ['ts'], 1: ['ts']}, delta_threshold=1, timestamp_field='ts',
                      timestamp_overlap=False)
        with make_reader(seq_dataset, schema_fields=ngram, workers_count=1,
                         shuffle_row_groups=False) as reader:
            starts = sorted(w[0].ts for w in reader)
        assert starts == list(range(0, 20, 2))

    @pytest.mark.parametrize('pool', ['dummy', 'thread', 'process'])
    def test_ngram_gapped_over_shuffled_pools(self, tmp_path_factory, pool):
        """Delta-threshold violations must be respected identically across every pool
        flavor with rowgroup+row shuffling on (model: reference
        test_ngram_end_to_end.py's reader-factory matrix)."""
        url = str(tmp_path_factory.mktemp('gapshuf') / 'ds')
        write_rows(url, SeqSchema, _seq_rows([0, 3, 8, 10, 11, 20, 23]),
                   rows_per_file=7, rowgroup_size_mb=64)
        ngram = NGram({0: ['ts', 'value'], 1: ['ts', 'label']}, delta_threshold=4,
                      timestamp_field='ts')
        with make_reader(url, schema_fields=ngram, reader_pool_type=pool,
                         workers_count=2, shuffle_row_groups=True, shuffle_rows=True,
                         seed=11) as reader:
            pairs = sorted((w[0].ts, w[1].ts) for w in reader)
        assert pairs == [(0, 3), (8, 10), (10, 11), (20, 23)]

    def test_ngram_windows_do_not_cross_rowgroups(self, tmp_path):
        """Rowgroup boundaries bound windows (reference caveat ngram.py:85-91): 20
        consecutive rows in 2 files -> the (9,10) pair must NOT be emitted."""
        url = str(tmp_path / 'split')
        write_rows(url, SeqSchema, _seq_rows(range(20)), rows_per_file=10,
                   rowgroup_size_mb=64)
        ngram = NGram({0: ['ts'], 1: ['ts']}, delta_threshold=1, timestamp_field='ts')
        with make_reader(url, schema_fields=ngram, workers_count=1,
                         shuffle_row_groups=False) as reader:
            starts = sorted(w[0].ts for w in reader)
        assert starts == [t for t in range(19) if t != 9]

    def test_ngram_no_overlap_with_drop_partitions_rejected(self, seq_dataset):
        ngram = NGram({0: ['ts'], 1: ['ts']}, delta_threshold=1, timestamp_field='ts',
                      timestamp_overlap=False)
        with pytest.raises(NotImplementedError, match='timestamp_overlap'):
            make_reader(seq_dataset, schema_fields=ngram, workers_count=1,
                        shuffle_row_drop_partitions=2)

    def test_ngram_with_predicate_rejected(self, seq_dataset):
        from petastorm_tpu.predicates import in_set
        ngram = NGram({0: ['ts'], 1: ['ts']}, delta_threshold=1, timestamp_field='ts')
        with pytest.raises(ValueError, match='NGram'):
            make_reader(seq_dataset, schema_fields=ngram,
                        predicate=in_set({1}, 'label'))

    def test_ngram_negative_offsets_end_to_end(self, seq_dataset):
        """Offsets {-1, 0, 1}: emitted keys keep their user-facing offsets and order
        rows correctly (model: reference test_ngram with negative shifts)."""
        ngram = NGram({-1: ['ts'], 0: ['ts', 'value'], 1: ['ts']}, delta_threshold=1,
                      timestamp_field='ts')
        with make_reader(seq_dataset, schema_fields=ngram, workers_count=1,
                         shuffle_row_groups=False) as reader:
            windows = list(reader)
        assert len(windows) == 18
        for w in windows:
            assert w[0].ts == w[-1].ts + 1
            assert w[1].ts == w[0].ts + 1

    def test_ngram_sparse_offsets_skip_middle_timestep(self, seq_dataset):
        """{0, 2} spans 3 rows but emits only the named offsets; the middle row still
        participates in the delta check."""
        ngram = NGram({0: ['ts'], 2: ['ts']}, delta_threshold=1, timestamp_field='ts')
        with make_reader(seq_dataset, schema_fields=ngram, workers_count=1,
                         shuffle_row_groups=False) as reader:
            windows = list(reader)
        assert len(windows) == 18
        for w in windows:
            assert set(w.keys()) == {0, 2}
            assert w[2].ts == w[0].ts + 2

    def test_ngram_shuffle_rows_permutes_but_preserves_set(self, seq_dataset):
        ngram = NGram({0: ['ts'], 1: ['ts']}, delta_threshold=1, timestamp_field='ts')

        def read(shuffle, seed=None):
            with make_reader(seq_dataset, schema_fields=ngram, workers_count=1,
                             shuffle_row_groups=False, shuffle_rows=shuffle,
                             seed=seed, reader_pool_type='dummy') as reader:
                return [w[0].ts for w in reader]

        ordered = read(False)
        shuffled = read(True, seed=3)
        assert ordered == sorted(ordered)
        assert shuffled != ordered
        assert sorted(shuffled) == ordered
        assert read(True, seed=3) == shuffled  # seeded => reproducible

    def test_ngram_overlapping_regexes_dedup(self, seq_dataset):
        """Patterns matching the same field twice must not produce duplicate namedtuple
        fields (regression: duplicate name ValueError on first window read)."""
        ngram = NGram({0: ['ts', 't.*'], 1: ['.*', 'label']}, delta_threshold=1,
                      timestamp_field='ts')
        with make_reader(seq_dataset, schema_fields=ngram, workers_count=1,
                         shuffle_row_groups=False) as reader:
            w = next(reader)
        assert set(w[0]._fields) == {'ts'}
        assert set(w[1]._fields) == {'ts', 'value', 'label'}

    def test_ngram_state_dict_supported(self, seq_dataset):
        # VERDICT r3 item 4: NGram readers checkpoint with the window as the row
        # unit (full resume coverage lives in test_checkpoint.py).
        ngram = NGram({0: ['ts'], 1: ['ts']}, delta_threshold=1, timestamp_field='ts')
        with make_reader(seq_dataset, schema_fields=ngram, workers_count=1,
                         num_epochs=1) as reader:
            next(reader)
            state = reader.state_dict()
        assert state['version'] == 1
        assert 'row_cursor' in state  # mid-piece: the window cursor is recorded


class TestNgramDeviceLayer:
    """NGram -> device layer (VERDICT r2 item 3; SURVEY.md §5.7's prescribed extension):
    window-major sequence batches through JaxDataLoader/InMemJaxLoader, including
    PartitionSpec('data', 'seq') sequence sharding on the virtual mesh."""

    def test_windows_as_arrays_matches_form_ngram(self):
        ngram = NGram({0: ['ts', 'value'], 1: ['ts', 'label']}, delta_threshold=4,
                      timestamp_field='ts')
        ngram.resolve_regex_field_names(SeqSchema)
        ts = np.array([0, 3, 8, 10, 11, 20, 30])
        columns = {'ts': ts, 'value': np.stack([np.array([t, t * 2]) for t in ts]),
                   'label': ts % 3}
        starts = ngram.form_ngram_columnar(ts)
        arrays = ngram.windows_as_arrays(columns, starts)
        assert arrays['ts'].shape == (3, 2)
        assert arrays['value'].shape == (3, 2, 2)
        np.testing.assert_array_equal(arrays['ts'], [[0, 3], [8, 10], [10, 11]])
        # every column covers the FULL window length (device-layer contract)
        np.testing.assert_array_equal(arrays['value'][:, 1, 0], [3, 10, 11])
        np.testing.assert_array_equal(arrays['label'], arrays['ts'] % 3)

    def test_windows_as_arrays_ragged_rejected(self):
        ngram = NGram({0: ['ts'], 1: ['ts']}, delta_threshold=1, timestamp_field='ts')
        with pytest.raises(ValueError, match='ragged'):
            ngram.windows_as_arrays({'ts': np.arange(3), 'r': [np.zeros(2), np.zeros(3),
                                                               np.zeros(1)]},
                                    np.array([0]))

    def test_jax_loader_window_batches(self, seq_dataset):
        from petastorm_tpu.parallel import JaxDataLoader
        ngram = NGram({0: ['ts', 'value'], 1: ['ts', 'value']}, delta_threshold=1,
                      timestamp_field='ts')
        with make_reader(seq_dataset, schema_fields=ngram, workers_count=1,
                         shuffle_row_groups=False, num_epochs=1) as reader:
            loader = JaxDataLoader(reader, batch_size=16, drop_last=True)
            batches = list(loader)
        assert len(batches) == 1  # 19 windows, drop_last
        batch = {k: np.asarray(v) for k, v in batches[0].items()}
        assert batch['ts'].shape == (16, 2)
        assert batch['value'].shape == (16, 2, 2)
        # window structure: consecutive timestamps, value = [ts, 2*ts] at every step
        np.testing.assert_array_equal(batch['ts'][:, 1], batch['ts'][:, 0] + 1)
        np.testing.assert_array_almost_equal(batch['value'][..., 0], batch['ts'])
        np.testing.assert_array_almost_equal(batch['value'][..., 1], batch['ts'] * 2)
        assert loader.stats.rows == 16

    def test_jax_loader_window_shuffling_buffer(self, seq_dataset):
        from petastorm_tpu.parallel import JaxDataLoader
        ngram = NGram({0: ['ts'], 1: ['ts']}, delta_threshold=1, timestamp_field='ts')

        def read(seed):
            with make_reader(seq_dataset, schema_fields=ngram, workers_count=1,
                             shuffle_row_groups=False, num_epochs=1) as reader:
                loader = JaxDataLoader(reader, batch_size=8, drop_last=False,
                                       shuffling_queue_capacity=16, seed=seed,
                                       device_put=False)
                return np.concatenate([b['ts'][:, 0] for b in loader])

        first = read(5)
        assert sorted(first.tolist()) == list(range(19))  # all windows, shuffled whole
        assert first.tolist() != sorted(first.tolist())
        np.testing.assert_array_equal(read(5), first)  # seeded => reproducible

    def test_jax_loader_sequence_sharded_train_step(self, seq_dataset):
        """Train a step from NGram windows on the virtual mesh with
        PartitionSpec('data', 'seq') sequence sharding (the VERDICT item's 'done')."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec
        from petastorm_tpu.parallel import JaxDataLoader, make_mesh
        ngram = NGram({i: ['ts', 'value'] for i in range(4)}, delta_threshold=1,
                      timestamp_field='ts')
        mesh = make_mesh(('data', 'seq'), (2, 4))
        with make_reader(seq_dataset, schema_fields=ngram, workers_count=1,
                         shuffle_row_groups=False, num_epochs=1) as reader:
            loader = JaxDataLoader(
                reader, batch_size=16, mesh=mesh,
                partition_spec={'value': PartitionSpec('data', 'seq'),
                                'ts': PartitionSpec('data', 'seq')})

            @jax.jit
            def train_step(w, batch):
                def loss_fn(w):
                    pred = jnp.einsum('blf,f->bl', batch['value'].astype(jnp.float32), w)
                    return jnp.mean((pred - batch['ts']) ** 2)
                loss, grad = jax.value_and_grad(loss_fn)(w)
                return w - 0.01 * grad, loss

            w = jnp.zeros((2,))
            losses = []
            for batch in loader:
                assert batch['value'].sharding.spec == PartitionSpec('data', 'seq')
                assert batch['value'].shape == (16, 4, 2)
                w, loss = train_step(w, batch)
                losses.append(float(loss))
        assert len(losses) == 1  # 17 windows of length 4, drop_last
        assert np.isfinite(losses[0])

    def test_inmem_loader_ngram_scan_epochs(self, seq_dataset):
        import jax.numpy as jnp
        from petastorm_tpu.parallel import InMemJaxLoader
        ngram = NGram({0: ['ts', 'value'], 1: ['ts', 'value']}, delta_threshold=1,
                      timestamp_field='ts')
        reader = make_reader(seq_dataset, schema_fields=ngram, workers_count=1,
                             shuffle_row_groups=False, num_epochs=1)
        loader = InMemJaxLoader(reader, batch_size=8, num_epochs=2, shuffle=True,
                                seed=1, drop_last=True)
        batches = list(loader)
        assert len(batches) == 4  # 19 windows -> 2 batches/epoch x 2 epochs
        for batch in batches:
            arr = np.asarray(batch['value'])
            assert arr.shape == (8, 2, 2)
            np.testing.assert_array_almost_equal(arr[..., 1], arr[..., 0] * 2)

        def step(carry, batch):
            return carry + jnp.sum(batch['value']), jnp.mean(batch['ts'])
        carry, aux = loader.scan_epochs(step, jnp.float32(0), num_epochs=1)
        assert np.isfinite(float(carry))

    def test_scan_stream_over_windows(self, seq_dataset):
        """Compiled-chunk streaming composes with NGram: window-major batches flow
        through scan_stream's chunk programs."""
        import jax.numpy as jnp
        from petastorm_tpu.parallel import JaxDataLoader
        ngram = NGram({0: ['ts', 'value'], 1: ['ts', 'value']}, delta_threshold=1,
                      timestamp_field='ts')
        with make_reader(seq_dataset, schema_fields=ngram, workers_count=1,
                         shuffle_row_groups=False, num_epochs=1) as reader:
            loader = JaxDataLoader(reader, batch_size=4)

            def step(carry, batch):
                assert batch['value'].shape == (4, 2, 2)
                return carry + jnp.sum(batch['value']), jnp.float32(0)

            carry, aux = loader.scan_stream(step, jnp.float32(0), chunk_batches=2)
        assert sum(int(np.asarray(a).shape[0]) for a in aux) == 4  # 19 windows // 4
        assert np.isfinite(float(carry))

    def test_inmem_mesh_scan_epochs_over_windows(self, seq_dataset):
        """NGram windows + mesh-sharded whole-epoch compilation compose: windows fill
        shard-blocked across the virtual mesh and scan_epochs trains from
        (batch, length, ...) sequence batches."""
        import jax.numpy as jnp
        from petastorm_tpu.parallel import InMemJaxLoader, make_mesh
        ngram = NGram({0: ['ts', 'value'], 1: ['ts', 'value']}, delta_threshold=1,
                      timestamp_field='ts')
        reader = make_reader(seq_dataset, schema_fields=ngram, workers_count=1,
                             shuffle_row_groups=False, num_epochs=1)
        loader = InMemJaxLoader(reader, batch_size=16, num_epochs=None, shuffle=True,
                                seed=4, mesh=make_mesh(('data',)), drop_last=True)

        def step(carry, batch):
            assert batch['value'].shape == (16, 2, 2)
            return carry + jnp.sum(batch['value']), jnp.min(batch['ts'])

        with pytest.warns(UserWarning, match='trailing rows'):
            # 19 windows over 8 shards -> 2/shard, 16 usable, 3 dropped
            carry, aux = loader.scan_epochs(step, jnp.float32(0), num_epochs=2)
        assert np.isfinite(float(carry))

    def test_loader_state_dict_supported_for_ngram(self, seq_dataset):
        # Window batches carry item identity (VERDICT r3 item 4), so the loader's
        # delivery-exact accounting works for NGram like any columnar reader.
        from petastorm_tpu.parallel import JaxDataLoader
        ngram = NGram({0: ['ts'], 1: ['ts']}, delta_threshold=1, timestamp_field='ts')
        with make_reader(seq_dataset, schema_fields=ngram, workers_count=1,
                         num_epochs=1) as reader:
            loader = JaxDataLoader(reader, batch_size=4, device_put=False)
            next(iter(loader))
            state = loader.state_dict()
        assert state['items_per_epoch'] == reader.items_per_epoch
