"""Direct unit tests for the unified columnar shuffling buffers (model: reference
petastorm/tests/test_shuffling_buffer.py, 238 LoC — add/retrieve contracts, capacity
and decorrelation floor, drain semantics), extended with torch-tensor columns since the
one implementation also replaces the reference's batched torch buffers
(pytorch_shuffling_buffer.py:22-279)."""
import numpy as np
import pytest
import torch

from petastorm_tpu.parallel.shuffling_buffer import (NoopShufflingBuffer,
                                                     RandomShufflingBuffer)


def _np_batch(start, n):
    return {'id': np.arange(start, start + n),
            'vec': np.arange(start, start + n, dtype=np.float32)[:, None] * [1.0, 2.0]}


def _torch_batch(start, n, device='cpu'):
    return {name: torch.as_tensor(col).to(device)
            for name, col in _np_batch(start, n).items()}


def _ids(batch):
    col = batch['id']
    return col.tolist() if hasattr(col, 'tolist') else list(col)


class TestNoopBuffer:
    def test_fifo_order_across_parts(self):
        buf = NoopShufflingBuffer()
        buf.add_many(_np_batch(0, 3))
        buf.add_many(_np_batch(3, 3))
        assert _ids(buf.retrieve(4)) == [0, 1, 2, 3]
        assert _ids(buf.retrieve(2)) == [4, 5]

    def test_retrieve_spanning_head_cursor(self):
        buf = NoopShufflingBuffer()
        buf.add_many(_np_batch(0, 5))
        assert _ids(buf.retrieve(2)) == [0, 1]
        buf.add_many(_np_batch(5, 2))
        assert _ids(buf.retrieve(5)) == [2, 3, 4, 5, 6]
        assert buf.size == 0

    def test_underflow_raises_until_finished(self):
        buf = NoopShufflingBuffer()
        buf.add_many(_np_batch(0, 2))
        with pytest.raises(RuntimeError):
            buf.retrieve(3)
        buf.finish()
        assert _ids(buf.retrieve(3)) == [0, 1]

    def test_add_after_finish_raises(self):
        buf = NoopShufflingBuffer()
        buf.finish()
        with pytest.raises(RuntimeError):
            buf.add_many(_np_batch(0, 1))

    def test_empty_add_is_noop(self):
        buf = NoopShufflingBuffer()
        buf.add_many({'id': np.array([], dtype=np.int64)})
        assert buf.size == 0
        assert not buf.can_retrieve(1)

    def test_can_retrieve_contract(self):
        buf = NoopShufflingBuffer()
        assert not buf.can_retrieve(1)
        buf.add_many(_np_batch(0, 2))
        assert buf.can_retrieve(2)
        assert not buf.can_retrieve(3)
        buf.finish()
        assert buf.can_retrieve(3)  # drain mode: anything >0 remaining

    def test_multicolumn_alignment_preserved(self):
        buf = NoopShufflingBuffer()
        buf.add_many(_np_batch(0, 4))
        out = buf.retrieve(3)
        np.testing.assert_array_equal(out['vec'][:, 0], out['id'].astype(np.float32))

    def test_ragged_list_columns(self):
        buf = NoopShufflingBuffer()
        buf.add_many({'id': np.arange(3), 'ragged': [[1], [2, 2], [3, 3, 3]]})
        out = buf.retrieve(2)
        assert out['ragged'] == [[1], [2, 2]]


class TestRandomBuffer:
    def test_min_after_floor_blocks_retrieval(self):
        buf = RandomShufflingBuffer(10, min_after_retrieve=4, seed=0)
        buf.add_many(_np_batch(0, 5))
        assert buf.can_retrieve(1)
        assert not buf.can_retrieve(2)
        with pytest.raises(RuntimeError):
            buf.retrieve(2)

    def test_min_after_gt_capacity_rejected(self):
        with pytest.raises(ValueError):
            RandomShufflingBuffer(4, min_after_retrieve=5)

    def test_can_add_respects_capacity(self):
        buf = RandomShufflingBuffer(4, 0, seed=0)
        assert buf.can_add()
        buf.add_many(_np_batch(0, 4))
        assert not buf.can_add()

    def test_row_set_preserved_no_duplicates(self):
        buf = RandomShufflingBuffer(100, 0, seed=7)
        for start in range(0, 30, 10):
            buf.add_many(_np_batch(start, 10))
        buf.finish()
        seen = []
        while buf.can_retrieve(1):
            seen.extend(_ids(buf.retrieve(7)))
        assert sorted(seen) == list(range(30))

    def test_seed_reproducible(self):
        def run():
            buf = RandomShufflingBuffer(50, 5, seed=42)
            buf.add_many(_np_batch(0, 30))
            out = _ids(buf.retrieve(10))
            buf.finish()
            while buf.can_retrieve(1):
                out.extend(_ids(buf.retrieve(10)))
            return out
        assert run() == run()

    def test_order_is_actually_shuffled(self):
        buf = RandomShufflingBuffer(1000, 0, seed=3)
        buf.add_many(_np_batch(0, 200))
        buf.finish()
        out = _ids(buf.retrieve(200))
        assert out != list(range(200))
        assert sorted(out) == list(range(200))

    def test_multicolumn_rows_stay_aligned_through_shuffle(self):
        buf = RandomShufflingBuffer(100, 0, seed=1)
        buf.add_many(_np_batch(0, 50))
        buf.finish()
        out = buf.retrieve(50)
        np.testing.assert_array_equal(out['vec'][:, 0], out['id'].astype(np.float32))
        np.testing.assert_array_equal(out['vec'][:, 1], 2.0 * out['id'])

    def test_drain_returns_partial_final_batch(self):
        buf = RandomShufflingBuffer(10, 2, seed=0)
        buf.add_many(_np_batch(0, 5))
        buf.finish()
        total = 0
        while buf.can_retrieve(1):
            total += len(_ids(buf.retrieve(4)))
        assert total == 5

    def test_add_after_finish_raises(self):
        buf = RandomShufflingBuffer(10, 0)
        buf.finish()
        with pytest.raises(RuntimeError):
            buf.add_many(_np_batch(0, 1))


class TestTorchColumns:
    """The same buffers natively hold torch tensors — the reference's batched torch
    buffer parity (pytorch_shuffling_buffer.py:22-279)."""

    def test_noop_fifo_torch(self):
        buf = NoopShufflingBuffer()
        buf.add_many(_torch_batch(0, 3))
        buf.add_many(_torch_batch(3, 3))
        out = buf.retrieve(5)
        assert torch.is_tensor(out['id'])
        assert _ids(out) == [0, 1, 2, 3, 4]

    def test_random_shuffle_torch_preserves_rows(self):
        buf = RandomShufflingBuffer(100, 0, seed=11)
        buf.add_many(_torch_batch(0, 20))
        buf.add_many(_torch_batch(20, 20))
        buf.finish()
        out = buf.retrieve(40)
        assert torch.is_tensor(out['id']) and torch.is_tensor(out['vec'])
        assert sorted(out['id'].tolist()) == list(range(40))
        assert torch.equal(out['vec'][:, 0], out['id'].to(out['vec'].dtype))

    def test_torch_device_preserved(self):
        buf = RandomShufflingBuffer(10, 0, seed=0)
        buf.add_many(_torch_batch(0, 4))
        buf.finish()
        out = buf.retrieve(4)
        assert out['id'].device.type == 'cpu'

    def test_mixed_numpy_and_torch_parts_coalesce(self):
        # Mixing array kinds across parts is tolerated: numpy concat absorbs cpu
        # tensors via __array__, so the head part's kind wins.
        buf = NoopShufflingBuffer()
        buf.add_many(_np_batch(0, 2))
        buf.add_many(_torch_batch(2, 2))
        assert _ids(buf.retrieve(4)) == [0, 1, 2, 3]


class TestBatchedDataLoaderDeviceBuffer:
    """BatchedDataLoader transforms columns to torch tensors before buffering, so the
    shuffle gathers tensors (reference CUDA-buffer contract)."""

    def test_batches_are_torch_and_complete(self, scalar_dataset):
        from petastorm_tpu.pytorch import BatchedDataLoader
        from petastorm_tpu.reader import make_batch_reader
        reader = make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                                   schema_fields=['id', 'float64'])
        seen = []
        with BatchedDataLoader(reader, batch_size=8,
                               shuffling_queue_capacity=32, seed=0) as loader:
            for batch in loader:
                assert torch.is_tensor(batch['id'])
                seen.extend(batch['id'].tolist())
        assert sorted(seen) == sorted(r['id'] for r in scalar_dataset.rows)

    def test_custom_transform_fn_controls_buffered_type(self, scalar_dataset):
        from petastorm_tpu.pytorch import BatchedDataLoader
        from petastorm_tpu.reader import make_batch_reader
        reader = make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                                   schema_fields=['id'])
        with BatchedDataLoader(reader, batch_size=4,
                               transform_fn=lambda col: np.asarray(col)) as loader:
            batch = next(iter(loader))
        assert isinstance(batch['id'], np.ndarray)
