"""Kitchen-sink integration: the features are exercised TOGETHER the way a real
training job stacks them — sharding x predicate x transform x pool flavor x
mesh-sharded loader x mid-stream checkpoint/resume. Each feature has its own suite;
these tests catch interactions between them."""
import numpy as np
import pytest

from petastorm_tpu import make_reader
from petastorm_tpu.parallel import JaxDataLoader, make_mesh
from petastorm_tpu.predicates import in_lambda
from petastorm_tpu.transform import TransformSpec


def _double_matrix(row):
    row['matrix'] = row['matrix'] * 2.0
    return row


def _id_mod3(id):
    # module-level: the process pool pickles predicates to worker processes
    return id % 3 == 0


def _id_mod2(id):
    return id % 2 == 0


TRANSFORM = TransformSpec(_double_matrix)


@pytest.mark.parametrize('pool', ['thread', 'process'])
def test_shard_predicate_transform_stack(synthetic_dataset, pool):
    """Both shards together, each through predicate + transform over a parallel pool,
    must reproduce exactly the predicate-selected rows with the transform applied."""
    wanted = {r['id'] for r in synthetic_dataset.rows if r['id'] % 3 == 0}
    seen = {}
    for shard in range(2):
        with make_reader(synthetic_dataset.url, reader_pool_type=pool,
                         workers_count=2, cur_shard=shard, shard_count=2,
                         schema_fields=['id', 'matrix'],
                         predicate=in_lambda(['id'], _id_mod3),
                         transform_spec=TRANSFORM,
                         shuffle_row_groups=True, seed=1) as reader:
            for row in reader:
                seen[int(row.id)] = row.matrix
    assert set(seen) == wanted
    by_id = {r['id']: r['matrix'] for r in synthetic_dataset.rows}
    for row_id, matrix in seen.items():
        np.testing.assert_allclose(matrix, by_id[row_id] * 2.0, rtol=1e-6)


def test_mesh_loader_over_sharded_transformed_readers(synthetic_dataset):
    """Mesh-sharded batches from per-shard readers cover the whole store once, with
    the transform visible in device-bound arrays."""
    mesh = make_mesh(('data',))
    covered = []
    for shard in range(2):
        reader = make_reader(synthetic_dataset.url, reader_pool_type='thread',
                             workers_count=2, cur_shard=shard, shard_count=2,
                             schema_fields=['id', 'matrix'],
                             transform_spec=TRANSFORM, shuffle_row_groups=False)
        # batch must divide over the 8-device mesh axis: drop_last trims the ragged
        # tail, so assert coverage up to at most one dropped partial batch per shard.
        with JaxDataLoader(reader, batch_size=8, mesh=mesh, drop_last=True) as loader:
            for batch in loader:
                assert batch['matrix'].shape[1:] == (4, 3)
                covered.extend(np.asarray(batch['id']).tolist())
    all_ids = {r['id'] for r in synthetic_dataset.rows}
    assert len(covered) == len(set(covered))  # no duplicates across shards
    assert set(covered) <= all_ids
    assert len(covered) >= len(all_ids) - 2 * 7


def test_checkpoint_resume_through_full_stack(synthetic_dataset):
    """Mid-stream resume with predicate + transform active: the union of rows
    delivered before and after the restart is exactly the predicate-selected set."""
    kwargs = dict(reader_pool_type='thread', workers_count=2,
                  schema_fields=['id', 'matrix'],
                  predicate=in_lambda(['id'], _id_mod2),
                  transform_spec=TRANSFORM, shuffle_row_groups=True, seed=5)
    wanted = sorted(r['id'] for r in synthetic_dataset.rows if r['id'] % 2 == 0)

    reader = make_reader(synthetic_dataset.url, **kwargs)
    # drop_last=False: coverage assertions must see the final partial batch
    loader = JaxDataLoader(reader, batch_size=7, device_put=False, drop_last=False)
    it = iter(loader)
    before = []
    for _ in range(2):
        before.extend(np.asarray(next(it)['id']).tolist())
    state = loader.state_dict()
    loader.stop()
    loader.join()

    resumed_reader = make_reader(synthetic_dataset.url, resume_state=state,
                                 **kwargs)
    after = []
    with JaxDataLoader(resumed_reader, batch_size=7, device_put=False,
                       drop_last=False) as loader2:
        for batch in loader2:
            after.extend(np.asarray(batch['id']).tolist())
    assert sorted(set(before) | set(after)) == wanted


def test_cache_epochs_shuffle_interaction(tmp_path):
    """Second epoch served through the local-disk cache must equal the first's row
    set even with per-epoch shuffling."""
    from test_common import create_test_dataset
    url = str(tmp_path / 'store')
    rows = create_test_dataset(url, num_rows=30, rows_per_file=10)
    with make_reader(url, reader_pool_type='thread', workers_count=2,
                     schema_fields=['id'], num_epochs=2, shuffle_row_groups=True,
                     shuffle_rows=True, seed=3, cache_type='local-disk',
                     cache_location=str(tmp_path / 'cache'),
                     cache_size_limit=10**8,
                     cache_row_size_estimate=1000) as reader:
        ids = [int(row.id) for row in reader]
    # Threaded completions interleave across the epoch boundary, so assert the
    # two-epoch multiset rather than a clean per-epoch split.
    from collections import Counter
    counts = Counter(ids)
    assert set(counts) == {r['id'] for r in rows}
    assert all(count == 2 for count in counts.values())
