"""Longitudinal performance observatory tests (ISSUE 18,
docs/observability.md "Longitudinal observatory"): the CRC-framed run-record
historian (restart survival, torn-tail tolerance, atomic rotation), the
trailing median/MAD compare + change-point attribution engine with its
exit-coded CLI, the live regression sentinel (Page-Hinkley drift matrix:
step drop fires exactly once, slow drift fires, noisy stationary never
false-positives) wired into the incident plane, and the satellites
(SloTracker ring-buffer history, autotune warm start, bench trailing-median
baseline)."""
import importlib.util
import json
import os
import struct
import zlib

import pytest

from petastorm_tpu import make_reader
from petastorm_tpu.telemetry.history import (COMPARE_EXIT_CODES,
                                             EXIT_BAD_STORE,
                                             HISTORY_BASENAME, HistoryPolicy,
                                             RunHistorian, build_run_record,
                                             compare_against_history,
                                             compare_records, fingerprint,
                                             last_good_record, load_records,
                                             read_history,
                                             resolve_history_policy,
                                             robust_baseline, run_platform,
                                             select_records,
                                             stage_time_shares,
                                             trailing_baseline)
from petastorm_tpu.telemetry.history import main as history_main
from petastorm_tpu.telemetry.registry import SECONDS_UNIT, MetricsRegistry
from petastorm_tpu.telemetry.sentinel import (DriftDetector,
                                              RegressionSentinel,
                                              SentinelPolicy,
                                              resolve_sentinel_policy)
from petastorm_tpu.telemetry.slo import SloTracker


def _record(rate=100.0, token='tok', platform='test-plat', owner='reader',
            shares=None, knobs=None, fingerprints=None, stamp=1000.0,
            efficiency=0.9):
    snapshot = {'histograms': {}}
    for stage, share in (shares or {}).items():
        snapshot['histograms'][stage] = {
            'unit': SECONDS_UNIT, 'count': 1, 'sum': share * 10.0,
            'max': 1.0, 'mean': 1.0, 'buckets': {}}
    return build_run_record(
        owner, token, elapsed_s=10.0, rows=int(rate * 10), snapshot=snapshot,
        slo_report={'efficiency': efficiency, 'wait_seconds': 1.0,
                    'primary_wait_stage': 'pool_wait'},
        fingerprints=fingerprints or {'config': 'abc'},
        knobs=knobs or {'decode_threads': 4.0},
        platform=platform, recorded_unix_s=stamp)


# ---------------------------------------------------------------------------
# journal discipline
# ---------------------------------------------------------------------------

class TestRunHistorianJournal:
    def test_round_trip_and_restart(self, tmp_path):
        path = str(tmp_path / 'hist.bin')
        historian = RunHistorian(path)
        for i in range(3):
            assert historian.append(_record(rate=100.0 + i, stamp=float(i)))
        # a NEW historian instance (process restart) replays the same store
        records, dropped = read_history(path)
        assert dropped == 0
        assert [r['recorded_unix_s'] for r in records] == [0.0, 1.0, 2.0]
        historian2 = RunHistorian(path)
        historian2.append(_record(stamp=3.0))
        records, dropped = read_history(path)
        assert len(records) == 4 and dropped == 0

    def test_torn_tail_is_dropped_not_fatal(self, tmp_path):
        path = str(tmp_path / 'hist.bin')
        historian = RunHistorian(path)
        for i in range(3):
            historian.append(_record(stamp=float(i)))
        with open(path, 'ab') as f:
            f.write(b'\x00\x00\x01\x00GARB')  # torn frame: header + short payload
        records, dropped = read_history(path)
        assert len(records) == 3 and dropped == 1
        # the next append heals the store: the torn frame triggers a
        # compaction that keeps the survivors AND the new record
        historian.append(_record(stamp=9.0))
        records, dropped = read_history(path)
        assert dropped == 0
        assert [r['recorded_unix_s'] for r in records] == [0.0, 1.0, 2.0, 9.0]

    def test_corrupt_crc_abandons_suffix(self, tmp_path):
        path = str(tmp_path / 'hist.bin')
        historian = RunHistorian(path)
        for i in range(3):
            historian.append(_record(stamp=float(i)))
        data = bytearray(open(path, 'rb').read())
        data[12] ^= 0xFF  # flip a byte inside the first frame's payload
        open(path, 'wb').write(bytes(data))
        records, dropped = read_history(path)
        assert records == [] and dropped == 1

    def test_rotation_keeps_newest(self, tmp_path):
        path = str(tmp_path / 'hist.bin')
        historian = RunHistorian(path, policy=HistoryPolicy(max_records=5),
                                 rotate_bytes=1)  # force rotation every append
        for i in range(8):
            historian.append(_record(stamp=float(i)))
        records, dropped = read_history(path)
        assert dropped == 0
        assert [r['recorded_unix_s'] for r in records] == [3.0, 4.0, 5.0,
                                                           6.0, 7.0]

    def test_missing_and_unreadable_store(self, tmp_path):
        assert load_records(str(tmp_path / 'absent.bin')) == ([], 0)
        assert load_records(None) == ([], 0)

    def test_newer_schema_records_are_skipped(self, tmp_path):
        path = str(tmp_path / 'hist.bin')
        historian = RunHistorian(path)
        historian.append(_record(stamp=1.0))
        payload = json.dumps({'schema': 999, 'kind': 'run'}).encode()
        with open(path, 'ab') as f:
            f.write(struct.Struct('>II').pack(len(payload),
                                              zlib.crc32(payload)) + payload)
        historian.append(_record(stamp=2.0))
        records, dropped = read_history(path)
        assert dropped == 0
        assert [r['recorded_unix_s'] for r in records] == [1.0, 2.0]

    def test_append_counter_and_state(self, tmp_path):
        registry = MetricsRegistry()
        historian = RunHistorian(str(tmp_path / 'hist.bin'),
                                 registry=registry)
        historian.append(_record())
        assert registry.snapshot()['counters']['history_record_written'] == 1
        state = historian.state()
        assert state['appended'] == 1 and state['frames_dropped'] == 0


class TestHistoryPolicy:
    def test_resolution_convention(self, tmp_path):
        assert resolve_history_policy(None) is None
        assert resolve_history_policy(False) is None
        assert resolve_history_policy(True) == HistoryPolicy()
        path = str(tmp_path / 's.bin')
        assert resolve_history_policy(path).path == path
        policy = HistoryPolicy(baseline_window=4)
        assert resolve_history_policy(policy) is policy
        with pytest.raises(ValueError):
            resolve_history_policy(42)

    def test_validation(self):
        with pytest.raises(ValueError):
            HistoryPolicy(max_records=0)
        with pytest.raises(ValueError):
            HistoryPolicy(baseline_window=0)
        with pytest.raises(ValueError):
            HistoryPolicy(noise_mads=-1.0)


# ---------------------------------------------------------------------------
# run records
# ---------------------------------------------------------------------------

class TestRunRecord:
    def test_stage_shares_are_unit_gated(self):
        snapshot = {'histograms': {
            'decode': {'unit': SECONDS_UNIT, 'count': 1, 'sum': 4.0,
                       'max': 1, 'mean': 1, 'buckets': {}},
            'row_bytes': {'unit': 1.0, 'count': 1, 'sum': 1e9,
                          'max': 1, 'mean': 1, 'buckets': {}},
            'cache_miss': {'unit': SECONDS_UNIT, 'count': 1, 'sum': 2.0,
                           'max': 1, 'mean': 1, 'buckets': {}},
        }}
        shares = stage_time_shares(snapshot, elapsed_s=10.0)
        # seconds-unit leaf stages only: the byte histogram and the
        # envelope-overlapped cache_miss stage never pollute the shares
        assert shares == {'decode': 0.4}

    def test_record_shape(self):
        record = _record(rate=100.0, shares={'decode': 0.3})
        assert record['schema'] == 1 and record['kind'] == 'run'
        assert record['rows_per_sec'] == 100.0
        assert record['stage_shares'] == {'decode': 0.3}
        assert record['storage'] == {'footer_cache_hit_rate': None,
                                     'hedge_win_rate': None}
        assert record['incidents'] == {'captured': 0, 'rate_limited': 0}
        json.dumps(record)  # JSON-safe end to end

    def test_fingerprint_is_stable_and_order_free(self):
        assert fingerprint({'a': 1, 'b': 2}) == fingerprint({'b': 2, 'a': 1})
        assert fingerprint({'a': 1}) != fingerprint({'a': 2})
        assert len(fingerprint({'a': 1})) == 12


# ---------------------------------------------------------------------------
# compare / attribution engine
# ---------------------------------------------------------------------------

class TestCompareEngine:
    def test_robust_baseline_median_mad(self):
        base = robust_baseline([100.0, 104.0, 96.0, 102.0, 1000.0])
        assert base['median'] == 102.0  # the outlier cannot drag the median
        assert base['mad'] == 2.0

    def test_select_and_trailing_baseline(self):
        records = ([_record(rate=100.0 + i, stamp=float(i)) for i in range(10)]
                   + [_record(token='other'), _record(platform='other')])
        assert len(select_records(records, 'tok', 'test-plat')) == 10
        baseline = trailing_baseline(records, 'tok', 'test-plat', window=4)
        assert baseline['count'] == 4
        assert baseline['rows_per_sec']['median'] == 107.5

    def test_insufficient_history(self):
        records = [_record(stamp=1.0)]
        report = compare_against_history(records, _record(stamp=2.0))
        assert report['verdict'] == 'insufficient-history'
        assert report['exit_code'] == COMPARE_EXIT_CODES[
            'insufficient-history']

    def test_same_config_within_noise(self):
        records = [_record(rate=100.0 + (i % 3), stamp=float(i))
                   for i in range(6)]
        candidate = _record(rate=101.0, stamp=99.0)
        report = compare_against_history(records, candidate)
        assert report['verdict'] == 'within-noise'
        assert report['exit_code'] == 0

    def test_deliberate_knob_change_attributes_and_regresses(self):
        records = [_record(rate=100.0 + (i % 3), stamp=float(i),
                           shares={'decode': 0.2}) for i in range(6)]
        candidate = _record(rate=50.0, stamp=99.0, shares={'decode': 0.5},
                            knobs={'decode_threads': 2.0},
                            fingerprints={'config': 'xyz'})
        report = compare_against_history(records, candidate)
        assert report['verdict'] == 'regressed'
        assert report['exit_code'] == COMPARE_EXIT_CODES['regressed']
        attribution = report['attribution']
        assert attribution['grown_stages'][0]['stage'] == 'decode'
        assert 'knob decode_threads 4 -> 2' in attribution['changed_knobs']
        assert any('config' in entry
                   for entry in attribution['changed_fingerprints'])
        # the one-line reason names the knob diff — the "decode share +18%,
        # knob decode_threads 4->2" surface the issue asks for
        assert 'decode_threads' in report['reason']
        assert 'decode share' in report['reason']

    def test_improvement_is_exit_coded_distinctly(self):
        records = [_record(rate=100.0, stamp=float(i)) for i in range(6)]
        report = compare_against_history(records, _record(rate=200.0,
                                                          stamp=99.0))
        assert report['verdict'] == 'improved'
        assert report['exit_code'] == COMPARE_EXIT_CODES['improved']

    def test_noise_band_capped_by_max_rel_delta(self):
        # one cold-start outlier blows the MAD past the median; the band
        # cap must still read a halved throughput as a regression
        records = [_record(rate=r, stamp=float(i)) for i, r in
                   enumerate([800.0, 11000.0, 15000.0, 10500.0])]
        candidate = _record(rate=4300.0, stamp=99.0)
        report = compare_against_history(records, candidate)
        assert report['noise_band_rows_per_sec'] <= \
            0.5 * report['baseline']['median_rows_per_sec']
        assert report['verdict'] == 'regressed'
        with pytest.raises(ValueError):
            HistoryPolicy(min_rel_delta=0.3, max_rel_delta=0.1)

    def test_candidate_excluded_from_its_own_baseline(self):
        records = [_record(rate=100.0, stamp=float(i)) for i in range(5)]
        candidate = _record(rate=50.0, stamp=99.0)
        records.append(candidate)
        report = compare_against_history(records, candidate)
        assert report['baseline']['count'] == 5
        assert report['verdict'] == 'regressed'

    def test_last_good_record_gates_warm_start(self):
        records = [_record(stamp=1.0), _record(stamp=2.0, rate=111.0)]
        newest = last_good_record(records, 'tok', 'test-plat')
        assert newest['rows_per_sec'] == 111.0
        assert last_good_record(records, 'absent-token') is None


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

class TestHistoryCli:
    def _store(self, tmp_path, candidate_rate=101.0):
        path = str(tmp_path / HISTORY_BASENAME)
        historian = RunHistorian(path)
        for i in range(6):
            historian.append(_record(rate=100.0 + (i % 3), stamp=float(i)))
        historian.append(_record(rate=candidate_rate, stamp=99.0))
        return path

    def test_list_and_show(self, tmp_path, capsys):
        path = self._store(tmp_path)
        assert history_main(['list', path]) == 0
        out = capsys.readouterr().out
        assert '7 record(s)' in out and 'token=tok' in out
        assert history_main(['show', path, '--index', '0']) == 0
        shown = json.loads(capsys.readouterr().out)
        assert shown['recorded_unix_s'] == 0.0

    def test_compare_exit_codes(self, tmp_path, capsys):
        within = self._store(tmp_path / 'a', candidate_rate=101.0)
        assert history_main(['compare', within]) == 0
        regressed = self._store(tmp_path / 'b', candidate_rate=50.0)
        assert history_main(['compare', regressed]) == COMPARE_EXIT_CODES[
            'regressed']
        improved = self._store(tmp_path / 'c', candidate_rate=200.0)
        assert history_main(['compare', improved]) == COMPARE_EXIT_CODES[
            'improved']
        capsys.readouterr()

    def test_compare_json_and_against(self, tmp_path, capsys):
        path = self._store(tmp_path, candidate_rate=50.0)
        code = history_main(['compare', path, '--json'])
        report = json.loads(capsys.readouterr().out)
        assert code == COMPARE_EXIT_CODES['regressed']
        assert report['verdict'] == 'regressed'
        # pairwise compare against one explicit record
        assert history_main(['compare', path, '--against', '0']
                            ) == COMPARE_EXIT_CODES['regressed']
        capsys.readouterr()

    def test_insufficient_history_exit(self, tmp_path, capsys):
        path = str(tmp_path / 'thin.bin')
        RunHistorian(path).append(_record(stamp=1.0))
        assert history_main(['compare', path]) == COMPARE_EXIT_CODES[
            'insufficient-history']
        capsys.readouterr()

    def test_missing_store_exit(self, tmp_path, capsys):
        assert history_main(['list', str(tmp_path / 'none.bin')]
                            ) == EXIT_BAD_STORE
        capsys.readouterr()

    def test_throughput_cli_dispatch(self, tmp_path, capsys):
        from petastorm_tpu.benchmark.cli import main as throughput_main
        path = self._store(tmp_path)
        assert throughput_main(['history', 'list', path]) == 0
        assert '7 record(s)' in capsys.readouterr().out


# ---------------------------------------------------------------------------
# drift detector matrix
# ---------------------------------------------------------------------------

class TestDriftDetectorMatrix:
    def test_step_drop_fires_exactly_once(self):
        sentinel = RegressionSentinel(SentinelPolicy(min_window_s=1.0),
                                      owner='reader')
        alarms = []
        sentinel._on_alarm = alarms.append
        rows, rate = 0, 1000
        for window in range(60):
            if window == 30:
                rate = 400  # one sustained collapse
            rows += rate
            sentinel.observe_sample(float(window + 1), rows)
        # edge-triggered: the NEW level becomes the baseline after the alarm,
        # so one collapse is one alarm, not one per subsequent window
        assert len(alarms) == 1
        evidence = alarms[0]
        assert evidence['series'] == 'rate'
        assert evidence['pre_rate_rows_per_sec'] > \
            evidence['post_rate_rows_per_sec']

    def test_slow_drift_fires(self):
        sentinel = RegressionSentinel(SentinelPolicy(min_window_s=1.0),
                                      owner='reader')
        rows, rate = 0, 1000.0
        for window in range(120):
            if window >= 20:
                rate *= 0.97  # -3%/window slow leak
            rows += rate
            sentinel.observe_sample(float(window + 1), int(rows))
        assert sentinel.alarms >= 1

    def test_noisy_stationary_never_false_positives(self):
        import random
        rng = random.Random(1234)
        sentinel = RegressionSentinel(SentinelPolicy(min_window_s=1.0),
                                      owner='reader')
        rows = 0.0
        for window in range(1000):
            rows += rng.uniform(900, 1100)  # +/-10% noise, level flat
            sentinel.observe_sample(float(window + 1), int(rows))
        assert sentinel.alarms == 0

    def test_wait_share_growth_fires_wait_series(self):
        sentinel = RegressionSentinel(SentinelPolicy(min_window_s=1.0),
                                      owner='loader')
        alarms = []
        sentinel._on_alarm = alarms.append
        rows, wait = 0, 0.0
        for window in range(60):
            rows += 1000  # rate stays flat: only the wait share grows
            wait += 0.02 if window < 30 else 0.6
            sentinel.observe_sample(float(window + 1), rows,
                                    wait_seconds=wait,
                                    primary_wait_stage='shuffle_wait')
        assert [a['series'] for a in alarms] == ['wait_share']
        assert alarms[0]['grown_stage'] == 'shuffle_wait'

    def test_detector_warmup_and_reset(self):
        detector = DriftDetector(delta=0.05, threshold=0.6, warmup=3,
                                 relative=True, direction='drop')
        for _ in range(3):
            assert not detector.update(1000.0)  # warmup builds the mean only
        assert not detector.update(1000.0)
        fired = any(detector.update(100.0) for _ in range(20))
        assert fired
        # full reset on alarm: the new level is the new baseline
        assert not any(detector.update(100.0) for _ in range(20))

    def test_due_gating_and_max_alarms(self):
        policy = SentinelPolicy(min_window_s=2.0, max_alarms=1)
        sentinel = RegressionSentinel(policy, owner='reader')
        assert sentinel.due(0.0)  # first sample always anchors
        sentinel.observe_sample(0.0, 0)
        assert not sentinel.due(1.0)
        assert sentinel.due(2.5)
        rows, rate = 0, 1000
        for window in range(200):
            if window and window % 40 == 0:
                rate = max(rate // 3, 1)  # repeated collapses
            rows += rate * 3
            sentinel.observe_sample(float(window + 1) * 3.0, rows)
        assert sentinel.alarms == 1  # capped
        assert not sentinel.due(1e9)

    def test_policy_resolution_and_validation(self):
        assert resolve_sentinel_policy(None) is None
        assert resolve_sentinel_policy(False) is None
        assert resolve_sentinel_policy(True) == SentinelPolicy()
        policy = SentinelPolicy(min_window_s=5.0)
        assert resolve_sentinel_policy(policy) is policy
        with pytest.raises(ValueError):
            resolve_sentinel_policy('nope')
        with pytest.raises(ValueError):
            SentinelPolicy(min_window_s=0.0)
        with pytest.raises(ValueError):
            SentinelPolicy(ewma_alpha=2.0)

    def test_report_and_gauges(self):
        registry = MetricsRegistry()
        sentinel = RegressionSentinel(SentinelPolicy(min_window_s=1.0),
                                      owner='reader', registry=registry,
                                      dataset_token='tok')
        rows = 0
        for window in range(5):
            rows += 1000
            sentinel.observe_sample(float(window + 1), rows)
        sentinel.export_gauges()
        report = sentinel.report()
        assert report['armed'] and report['owner'] == 'reader'
        assert report['windows'] == 4 and report['alarms'] == 0
        gauges = registry.snapshot()['gauges']
        assert gauges['sentinel_rate_ewma'] == pytest.approx(1000.0)
        # no wait series was fed: the wait gauge must not export a fake 0.0
        assert 'sentinel_wait_share_ewma' not in gauges


# ---------------------------------------------------------------------------
# sentinel -> incident plane
# ---------------------------------------------------------------------------

class TestSentinelIncidentPlane:
    def _collapse(self, sentinel):
        rows, rate = 0, 1000
        for window in range(60):
            if window == 30:
                rate = 300
            rows += rate
            sentinel.observe_sample(float(window + 1), rows)

    def test_collapse_captures_exactly_one_bundle(self, tmp_path):
        from petastorm_tpu.telemetry.incident import (IncidentPolicy,
                                                      IncidentRecorder,
                                                      scan_bundles)
        registry = MetricsRegistry()
        recorder = IncidentRecorder(str(tmp_path), IncidentPolicy(),
                                    registry=registry)
        sentinel = RegressionSentinel(SentinelPolicy(min_window_s=1.0),
                                      owner='reader', registry=registry,
                                      incidents=recorder,
                                      dataset_token='tok')
        recorder.add_source('sentinel', sentinel.report)
        self._collapse(sentinel)
        bundles = scan_bundles(str(tmp_path))
        kinds = [entry['kind'] for entry in bundles]
        assert kinds.count('perf_regression') == 1
        assert registry.snapshot()['counters']['perf_regression'] == 1

    def test_bundle_autopsy_sees_the_sentinel_evidence(self, tmp_path):
        from petastorm_tpu.telemetry.incident import (IncidentPolicy,
                                                      IncidentRecorder,
                                                      analyze_bundle,
                                                      scan_bundles)
        recorder = IncidentRecorder(str(tmp_path), IncidentPolicy())
        sentinel = RegressionSentinel(SentinelPolicy(min_window_s=1.0),
                                      owner='reader', incidents=recorder,
                                      dataset_token='tok')
        recorder.add_source('sentinel', sentinel.report)
        self._collapse(sentinel)
        bundle = scan_bundles(str(tmp_path))[0]['path']
        report = analyze_bundle(bundle)
        assert report['trigger'] == 'perf_regression'
        assert any('regression sentinel fired' in clue
                   for cause in report['causes']
                   for clue in cause.get('evidence', []))

    def test_undisturbed_run_captures_nothing(self, tmp_path):
        from petastorm_tpu.telemetry.incident import (IncidentPolicy,
                                                      IncidentRecorder,
                                                      scan_bundles)
        recorder = IncidentRecorder(str(tmp_path), IncidentPolicy())
        sentinel = RegressionSentinel(SentinelPolicy(min_window_s=1.0),
                                      owner='reader', incidents=recorder)
        rows = 0
        for window in range(100):
            rows += 1000
            sentinel.observe_sample(float(window + 1), rows)
        assert sentinel.alarms == 0
        assert scan_bundles(str(tmp_path)) == []

    def test_dead_recorder_never_breaks_the_run(self):
        class Exploding:
            def trigger(self, *a, **k):
                raise RuntimeError('recorder died')
        sentinel = RegressionSentinel(SentinelPolicy(min_window_s=1.0),
                                      owner='reader', incidents=Exploding())
        self._collapse(sentinel)
        assert sentinel.alarms == 1  # alarm recorded, exception swallowed


# ---------------------------------------------------------------------------
# reader / loader / dispatcher wiring
# ---------------------------------------------------------------------------

class TestReaderHistoryWiring:
    def test_off_path_builds_nothing(self, synthetic_dataset):
        with make_reader(synthetic_dataset.url, schema_fields=['id'],
                         workers_count=1, num_epochs=1) as reader:
            assert reader._history is None
            assert reader._sentinel is None
            assert reader.history_report() is None
            for _ in reader:
                pass
        dataset_path = synthetic_dataset.url[len('file://'):]
        assert not os.path.exists(os.path.join(dataset_path,
                                               HISTORY_BASENAME))

    def test_two_runs_record_two_comparable_records(self, tmp_path,
                                                    synthetic_dataset):
        store = str(tmp_path / 'hist.bin')
        for _ in range(2):
            with make_reader(synthetic_dataset.url, schema_fields=['id'],
                             workers_count=1, num_epochs=1,
                             history=store) as reader:
                for _ in reader:
                    pass
                token = reader.dataset_token
        records, dropped = load_records(store)
        assert dropped == 0 and len(records) == 2
        for record in records:
            assert record['owner'] == 'reader'
            assert record['dataset_token'] == token
            assert record['platform'] == run_platform()
            assert record['rows'] > 0 and record['rows_per_sec'] > 0
            assert record['fingerprints']['config']
            assert 'decode_threads' in record['knobs']
        # identical construction: identical config fingerprint
        assert (records[0]['fingerprints']['config']
                == records[1]['fingerprints']['config'])

    def test_stop_is_idempotent_one_record(self, tmp_path, synthetic_dataset):
        store = str(tmp_path / 'hist.bin')
        reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                             workers_count=1, num_epochs=1, history=store)
        for _ in reader:
            pass
        reader.stop()
        reader.stop()
        reader.join()
        records, _ = load_records(store)
        assert len(records) == 1

    def test_diagnostics_and_sentinel_armed(self, tmp_path,
                                            synthetic_dataset):
        store = str(tmp_path / 'hist.bin')
        with make_reader(synthetic_dataset.url, schema_fields=['id'],
                         workers_count=1, num_epochs=1,
                         history=store) as reader:
            assert reader._sentinel is not None
            for _ in reader:
                pass
            diag = reader.diagnostics
            assert diag['history']['path'] == store
            assert diag['sentinel']['owner'] == 'reader'

    def test_warm_start_seeds_from_last_good_record(self, tmp_path,
                                                    synthetic_dataset):
        from petastorm_tpu.autotune import AutotunePolicy
        store = str(tmp_path / 'hist.bin')
        with make_reader(synthetic_dataset.url, schema_fields=['id'],
                         workers_count=1, num_epochs=1,
                         history=store) as reader:
            for _ in reader:
                pass
        records, _ = load_records(store)
        forged = dict(records[-1])
        forged['knobs'] = dict(forged['knobs'],
                               ventilator_max_in_flight=5.0)
        RunHistorian(store).append(forged)
        policy = AutotunePolicy(warm_start=True, warmup_windows=1000)
        with make_reader(synthetic_dataset.url, schema_fields=['id'],
                         workers_count=1, num_epochs=1, history=store,
                         autotune=policy) as reader:
            decisions = reader.autotune_report()['decisions']
            seeded = [d for d in decisions if d['action'] == 'warm_start']
            assert any(d['knob'] == 'ventilator_max_in_flight'
                       and d['to'] == 5.0 for d in seeded)
            for _ in reader:
                pass

    def test_warm_start_gated_off_without_comparable_record(
            self, tmp_path, synthetic_dataset):
        from petastorm_tpu.autotune import AutotunePolicy
        policy = AutotunePolicy(warm_start=True, warmup_windows=1000)
        with make_reader(synthetic_dataset.url, schema_fields=['id'],
                         workers_count=1, num_epochs=1,
                         history=str(tmp_path / 'empty.bin'),
                         autotune=policy) as reader:
            decisions = reader.autotune_report()['decisions']
            assert [d for d in decisions
                    if d['action'] == 'warm_start'] == []
            for _ in reader:
                pass


class TestLoaderHistoryWiring:
    def test_loader_and_reader_both_record(self, tmp_path,
                                           synthetic_dataset):
        from petastorm_tpu.parallel import JaxDataLoader
        store = str(tmp_path / 'hist.bin')
        reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                             workers_count=1, num_epochs=1, history=store)
        with JaxDataLoader(reader, batch_size=8, history=True) as loader:
            for _ in loader:
                pass
        records, dropped = load_records(store)
        assert dropped == 0
        owners = sorted(record['owner'] for record in records)
        assert owners == ['loader', 'reader']
        loader_record = next(r for r in records if r['owner'] == 'loader')
        assert 'loader' in loader_record['fingerprints']

    def test_loader_without_store_warns_and_disables(self, tmp_path,
                                                     synthetic_dataset):
        import warnings as warnings_module
        from petastorm_tpu.parallel import JaxDataLoader
        reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                             workers_count=1, num_epochs=1)
        with warnings_module.catch_warnings(record=True) as caught:
            warnings_module.simplefilter('always')
            with JaxDataLoader(reader, batch_size=8,
                               history=True) as loader:
                assert loader._history is None
                for _ in loader:
                    pass
        assert any('no store path' in str(w.message) for w in caught)


class TestDispatcherHistoryWiring:
    def test_dispatcher_records_one_service_record(self, tmp_path):
        pytest.importorskip('zmq')
        from petastorm_tpu.service.dispatcher import (SERVICE_DATASET_TOKEN,
                                                      Dispatcher)
        store = str(tmp_path / 'service-hist.bin')
        dispatcher = Dispatcher(history=store)
        dispatcher.start()
        state = dispatcher.state()
        assert state['history']['path'] == store
        assert state['sentinel']['owner'] == 'dispatcher'
        dispatcher.stop()
        dispatcher.join()
        records, dropped = load_records(store)
        assert dropped == 0 and len(records) == 1
        assert records[0]['owner'] == 'dispatcher'
        assert records[0]['dataset_token'] == SERVICE_DATASET_TOKEN
        assert records[0]['fingerprints']['config']

    def test_history_true_arms_sentinel_only(self):
        pytest.importorskip('zmq')
        from petastorm_tpu.service.dispatcher import Dispatcher
        dispatcher = Dispatcher(history=True)
        assert dispatcher._history is None  # no dataset home to default into
        assert dispatcher._sentinel is not None
        assert dispatcher.history_report() is None

    def test_fleet_resolves_a_store_under_its_cache_dir(self, tmp_path):
        pytest.importorskip('zmq')
        from petastorm_tpu.service.fleet import ServiceFleet
        fleet = ServiceFleet(workers=0, cache_dir=str(tmp_path),
                             history=True)
        assert fleet.history_path == str(tmp_path / HISTORY_BASENAME)
        assert fleet.dispatcher._history is not None


# ---------------------------------------------------------------------------
# satellites: SLO ring buffer, bench trailing baseline
# ---------------------------------------------------------------------------

class TestSloHistoryRingBuffer:
    def _snapshot(self):
        return {'histograms': {'pool_wait': {
            'unit': SECONDS_UNIT, 'count': 1, 'sum': 0.5, 'max': 0.5,
            'mean': 0.5, 'buckets': {}}}}

    def test_ring_buffer_bounds_and_shape(self):
        tracker = SloTracker(history_size=4)
        for i in range(6):
            report = tracker.evaluate(self._snapshot(), elapsed_s=2.0 + i,
                                      rows=100)
        assert len(report['history']) == 4
        point = report['history'][-1]
        assert sorted(point) == ['breached', 'efficiency', 'elapsed_s',
                                 'goodput_rows_per_sec', 'wait_seconds']
        assert len(tracker.history()) == 4

    def test_warmup_windows_never_enter_history(self):
        tracker = SloTracker()
        report = tracker.evaluate(self._snapshot(), elapsed_s=0.1)
        assert report['history'] == []

    def test_reader_vars_carry_the_history(self, tmp_path,
                                           synthetic_dataset):
        with make_reader(synthetic_dataset.url, schema_fields=['id'],
                         workers_count=1, num_epochs=1,
                         history=str(tmp_path / 'h.bin')) as reader:
            for _ in reader:
                pass
            snapshot, report = reader._snapshot_with_slo()
            assert snapshot['slo_history'] == report['history']


class TestBenchTrailingBaseline:
    def _load_bench(self):
        spec = importlib.util.spec_from_file_location(
            'bench_module_history',
            os.path.join(os.path.dirname(__file__), '..', 'bench.py'))
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        return module

    def test_trailing_median_absorbs_one_outlier_round(self, tmp_path):
        bench = self._load_bench()
        rounds = [
            {'parsed': {'platform': 'cpu', 'streaming_rows_per_sec': 100.0}},
            {'parsed': {'platform': 'cpu', 'streaming_rows_per_sec': 20.0}},
            {'parsed': {'platform': 'cpu', 'streaming_rows_per_sec': 104.0}},
        ]
        for i, payload in enumerate(rounds):
            path = tmp_path / 'BENCH_r{:02d}.json'.format(i + 1)
            path.write_text(json.dumps(payload))
            os.utime(str(path), (i + 1, i + 1))
        paths = bench.trailing_bench_baselines(str(tmp_path), window=3)
        baseline, used = bench.trailing_median_baseline(
            {'platform': 'cpu'}, paths)
        assert len(used) == 3
        # the r02 outlier round cannot drag the reference down to 20
        assert baseline['streaming_rows_per_sec'] == 100.0
        regressions = bench.compare_to_baseline(
            {'platform': 'cpu', 'streaming_rows_per_sec': 50.0}, baseline)
        assert regressions[0]['drop_pct'] == 50.0

    def test_cross_platform_rounds_compare_to_nothing(self, tmp_path):
        bench = self._load_bench()
        path = tmp_path / 'BENCH_r01.json'
        path.write_text(json.dumps(
            {'parsed': {'platform': 'tpu',
                        'streaming_rows_per_sec': 5000.0}}))
        baseline, used = bench.trailing_median_baseline(
            {'platform': 'cpu'},
            bench.trailing_bench_baselines(str(tmp_path)))
        assert baseline is None and used == []

    def test_history_section_registered(self):
        bench = self._load_bench()
        assert 'history' in bench.SECTION_NAMES
        assert 'history' in bench.SECTION_RUN_ORDER
        assert sorted(bench.SECTION_RUN_ORDER) == sorted(bench.SECTION_NAMES)
