"""Spark adapter behavior under a stub pyspark (pyspark is not installed in this
environment — VERDICT round 1 item 9: unit-test the branch with a stub and document the
pure-Arrow ``write_rows`` as the first-class write path).

The stubs emulate exactly the pyspark surface the adapters touch: ``pyspark.sql.Row``,
``DataFrame`` (for the converter dispatch), ``df.write.option().parquet`` (backed by a
REAL pyarrow parquet write so ``open_dataset`` sees genuine files), and
``rdd.map``."""

import os
import sys
import types

import numpy as np
import pytest


class _StubRow(object):
    """pyspark.sql.Row semantics: Row('a','b') -> ordered row class; instance holds
    positional values."""

    def __new__(cls, *names):
        template = object.__new__(cls)
        template._names = list(names)
        template._values = None

        def call(*values):
            inst = object.__new__(_StubRow)
            inst._names = template._names
            inst._values = list(values)
            return inst
        template._call = call
        return template

    def __call__(self, *values):
        return self._call(*values)


@pytest.fixture
def stub_pyspark(monkeypatch):
    pyspark = types.ModuleType('pyspark')
    sql = types.ModuleType('pyspark.sql')

    class DataFrame(object):
        pass

    sql.Row = _StubRow
    sql.DataFrame = DataFrame
    pyspark.sql = sql
    monkeypatch.setitem(sys.modules, 'pyspark', pyspark)
    monkeypatch.setitem(sys.modules, 'pyspark.sql', sql)
    return pyspark


class TestDictToSparkRow:
    def test_encodes_and_orders(self, stub_pyspark):
        from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
        from petastorm_tpu.spark_utils import dict_to_spark_row
        from petastorm_tpu.unischema import Unischema, UnischemaField
        schema = Unischema('S', [
            UnischemaField('b', np.int64, (), ScalarCodec(), False),
            UnischemaField('a', np.float32, (2,), NdarrayCodec(), False),
        ])
        row = dict_to_spark_row(schema, {'b': 3, 'a': np.zeros(2, np.float32)})
        assert row._names == ['b', 'a']  # schema order, not alphabetical
        assert row._values[0] == 3
        assert isinstance(row._values[1], bytes)  # codec-encoded

    def test_nullability_validated(self, stub_pyspark):
        from petastorm_tpu.codecs import ScalarCodec
        from petastorm_tpu.spark_utils import dict_to_spark_row
        from petastorm_tpu.unischema import Unischema, UnischemaField
        schema = Unischema('S', [UnischemaField('x', np.int64, (), ScalarCodec(), False)])
        with pytest.raises(ValueError, match='not nullable'):
            dict_to_spark_row(schema, {'x': None})
        with pytest.raises(ValueError, match='not part of schema'):
            dict_to_spark_row(schema, {'x': 1, 'extra': 2})

    def test_requires_pyspark(self):
        from petastorm_tpu.spark_utils import dict_to_spark_row
        from petastorm_tpu.unischema import Unischema
        if 'pyspark' in sys.modules:
            pytest.skip('real pyspark present')
        with pytest.raises(ImportError, match='write_rows'):
            dict_to_spark_row(Unischema('S', []), {})


class _StubWriter(object):
    def __init__(self, table):
        self._table = table
        self.options = {}

    def option(self, key, value):
        self.options[key] = value
        return self

    def parquet(self, path):
        import pyarrow.parquet as pq
        os.makedirs(path, exist_ok=True)
        pq.write_table(self._table, os.path.join(path, 'part-0.parquet'))


@pytest.fixture
def stub_spark_df(stub_pyspark):
    """A pyspark-shaped DataFrame whose .write.parquet produces REAL parquet files."""
    import pyarrow as pa

    class StubDataFrame(stub_pyspark.sql.DataFrame):
        def __init__(self, data):
            self._table = pa.table(data)
            self.write = _StubWriter(self._table)

        def count(self):
            return self._table.num_rows

    return StubDataFrame


class TestConverterSparkBranch:
    def test_spark_dataframe_materializes(self, stub_spark_df, tmp_path):
        from petastorm_tpu.converter import make_converter
        df = stub_spark_df({'id': list(range(20)), 'value': [i / 2 for i in range(20)]})
        converter = make_converter(df, parent_cache_dir_url=str(tmp_path))
        try:
            assert converter.dataset_size == 20
            assert converter.file_urls
            # block size option threaded through (reference converter row group MB)
            assert 'parquet.block.size' in df.write.options
            with converter.make_jax_loader(batch_size=10,
                                           loader_kwargs={'device_put': False}) as loader:
                total = sum(len(batch['id']) for batch in loader)
            assert total == 20
        finally:
            converter.delete()
        assert not os.path.exists(converter.cache_dir_url)


class TestDatasetAsRdd:
    def test_decodes_namedtuples(self, stub_pyspark, synthetic_dataset):
        from petastorm_tpu.spark_utils import dataset_as_rdd

        class StubRecord(object):
            def __init__(self, d):
                self._d = d

            def asDict(self):
                return dict(self._d)

        class StubRdd(object):
            def __init__(self, records):
                self._records = records

            def map(self, fn):
                return [fn(r) for r in self._records]

        class StubRead(object):
            def __init__(self, url):
                self._url = url

            def parquet(self, url):
                import pyarrow.parquet as pq
                table = pq.read_table(url[len('file://'):]
                                      if url.startswith('file://') else url)
                self._table = table
                return self

            def select(self, *names):
                self._names = list(names)
                return self

            @property
            def rdd(self):
                rows = self._table.select(self._names).to_pylist()
                return StubRdd([StubRecord(r) for r in rows])

        class StubSession(object):
            read = StubRead(None)

        rows = dataset_as_rdd(synthetic_dataset.url, StubSession(),
                              schema_fields=['id', 'matrix'])
        assert len(rows) == len(synthetic_dataset.rows)
        by_id = {r.id: r for r in rows}
        source = synthetic_dataset.rows[0]
        np.testing.assert_array_almost_equal(by_id[source['id']].matrix,
                                             source['matrix'])