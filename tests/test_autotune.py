"""Tests for the closed-loop autotuner (petastorm_tpu/autotune/,
docs/autotuning.md) and the runtime knob mutators it actuates.

Four layers:

- **mutators**: the bounded ``set_*`` surfaces grown for the actuation layer
  (ventilator in-flight window, thread-pool elastic grow/park, shm ring
  config, shuffle-buffer threshold, cache modes, service scheduler windows)
  resize correctly mid-epoch;
- **controller units**: the hill-climb state machine with a fake clock and
  scripted telemetry — commit, revert+cooldown+direction-flip, breaker
  interlock freeze/unfreeze, one-knob-at-a-time, warmup, measure-only;
- **scripted convergence**: a deterministic simulated pipeline where rows/s is
  a known function of the knob — the controller started from the degraded
  value converges to >= the fixed-default rate within a bounded window count;
- **e2e**: a real reader started with deliberately bad knobs (1 worker,
  in-flight window 1) self-improves mid-epoch, the disabled path stays
  byte-identical, and the loader/service integrations register their knobs.
"""
import json
import os
import queue
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from petastorm_tpu.autotune import (AutotuneController, AutotunePolicy,
                                    KNOB_IDS, Knob, KnobCatalog,
                                    build_loader_knobs, build_service_knobs,
                                    resolve_policy, snapshot_delta)
from petastorm_tpu.telemetry.registry import SECONDS_UNIT


# ---------------------------------------------------------------- helpers


def _stage_snapshot(stage, seconds, count=10):
    """A cumulative telemetry snapshot with one latency stage."""
    return {'histograms': {stage: {'unit': SECONDS_UNIT, 'count': count,
                                   'sum': seconds, 'max': seconds}}}


class ScriptedPipeline(object):
    """A deterministic fake pipeline: cumulative rows advance by
    ``rate_for(knob_value)`` per clock tick; telemetry always blames
    ``pool_wait`` so the default chooser picks the one knob."""

    def __init__(self, rate_for, initial=1.0, minimum=1.0, maximum=16.0,
                 step=1.0):
        self.rate_for = rate_for
        self.value = initial
        self.clock_now = 0.0
        self.rows = 0.0
        self.cum_seconds = 0.0
        self.knob = Knob('pool_workers', 'scripted worker count',
                         minimum=minimum, maximum=maximum, step=step,
                         cost='cheap', stages=('pool_wait',),
                         get=lambda: self.value, apply=self._apply)

    def _apply(self, value):
        self.value = value
        return value

    def tick(self):
        """Advance one window: one second of clock, rate_for(value) rows."""
        self.clock_now += 1.0
        self.rows += self.rate_for(self.value)
        self.cum_seconds += 0.5

    def snapshot(self):
        return _stage_snapshot('pool_wait', self.cum_seconds,
                               count=int(self.clock_now * 10) + 1)


def make_controller(pipeline, policy=None, breakers=None, **kwargs):
    breakers_fn = breakers if breakers is not None else (lambda: {})
    return AutotuneController(
        KnobCatalog([pipeline.knob]),
        metric_fn=lambda: pipeline.rows,
        snapshot_fn=pipeline.snapshot,
        policy=policy or AutotunePolicy(window_s=1.0, warmup_windows=1,
                                        hold_windows=1, min_improvement=0.02,
                                        cooldown_windows=3),
        breaker_snapshot_fn=breakers_fn,
        clock=lambda: pipeline.clock_now,
        name='test',
        **kwargs)


def drive(controller, pipeline, windows):
    decisions = []
    for _ in range(windows):
        pipeline.tick()
        decision = controller.step()
        if decision is not None:
            decisions.append(decision)
    return decisions


# ---------------------------------------------------------------- policy


def test_resolve_policy_forms():
    assert resolve_policy(None) is None
    assert resolve_policy(False) is None
    assert isinstance(resolve_policy(True), AutotunePolicy)
    policy = AutotunePolicy(window_s=9.0)
    assert resolve_policy(policy) is policy
    with pytest.raises(ValueError):
        resolve_policy('yes')


def test_policy_validation():
    with pytest.raises(ValueError):
        AutotunePolicy(window_s=0)
    with pytest.raises(ValueError):
        AutotunePolicy(min_improvement=-0.1)
    with pytest.raises(ValueError):
        AutotunePolicy(cooldown_windows=0)


# ---------------------------------------------------------------- knobs


def test_knob_rejects_undeclared_id_and_cost():
    ok = dict(description='x', minimum=0.0, maximum=1.0, step=1.0,
              cost='cheap', stages=(), get=lambda: 0.0, apply=lambda v: v)
    with pytest.raises(ValueError):
        Knob('not_a_knob', **ok)
    with pytest.raises(ValueError):
        Knob('pool_workers', **dict(ok, cost='free'))
    with pytest.raises(ValueError):
        Knob('pool_workers', **dict(ok, minimum=2.0, maximum=1.0))
    assert 'pool_workers' in KNOB_IDS


def test_catalog_lookup_and_stage_map():
    knob = Knob('decode_threads', 'x', minimum=1.0, maximum=8.0, step=1.0,
                cost='cheap', stages=('decode',), get=lambda: 2.0,
                apply=lambda v: v)
    catalog = KnobCatalog([knob])
    assert catalog.knob('decode_threads') is knob
    assert 'decode_threads' in catalog
    assert catalog.knobs_for_stage('decode') == [knob]
    assert catalog.knobs_for_stage('h2d') == []
    as_dicts = catalog.as_dicts()
    assert as_dicts['decode_threads']['value'] == 2.0
    assert as_dicts['decode_threads']['stages'] == ['decode']


# ------------------------------------------------------------- mutators


def test_ventilator_max_in_flight_resizes_mid_epoch():
    from petastorm_tpu.workers.ventilator import ConcurrentVentilator
    ventilated = []
    vent = ConcurrentVentilator(
        ventilate_fn=lambda **kw: ventilated.append(kw),
        items_to_ventilate=[{'i': i} for i in range(10)],
        iterations=1, max_ventilation_queue_size=1)
    vent.start()
    deadline = time.time() + 5
    while len(ventilated) < 1 and time.time() < deadline:
        time.sleep(0.01)
    time.sleep(0.15)  # the window is 1: nothing further may ventilate
    assert len(ventilated) == 1
    assert vent.max_in_flight == 1
    assert vent.set_max_in_flight(4) == 4
    deadline = time.time() + 5
    while len(ventilated) < 4 and time.time() < deadline:
        time.sleep(0.01)
    assert len(ventilated) == 4  # grew to the new window without any ack
    with pytest.raises(ValueError):
        vent.set_max_in_flight(0)
    vent.stop()


class _IdWorker(object):
    """Records which worker id processed each item (thread-pool tests)."""

    def __init__(self, worker_id, publish_func, args):
        self.worker_id = worker_id
        self._publish = publish_func
        self._sink = args

    def process(self, **kwargs):
        self._sink.put((self.worker_id, kwargs['i']))
        self._publish({'worker': self.worker_id, 'i': kwargs['i']})

    def shutdown(self):
        pass


def test_thread_pool_elastic_grow_and_park():
    from petastorm_tpu.workers.thread_pool import ThreadPool
    sink = queue.Queue()
    pool = ThreadPool(1, results_queue_size=1000, max_workers_count=3)
    pool.start(_IdWorker, sink)
    assert pool.set_workers_count(100) == 3  # clamped to max_workers_count
    assert pool.workers_count == 3
    assert len(pool._threads) == 3  # growth spawned real threads mid-run
    for i in range(30):
        pool.ventilate(i=i)
    seen = [sink.get(timeout=10) for _ in range(30)]
    assert {i for _, i in seen} == set(range(30))
    # shrink to 1: parked workers take no further items (a worker already
    # blocked inside queue.get may grab ONE more item before it reaches the
    # park point — the park is at the item boundary, nothing is killed)
    assert pool.set_workers_count(0) == 1  # clamped low
    for i in range(30, 60):
        pool.ventilate(i=i)
    seen = [sink.get(timeout=10) for _ in range(30)]
    assert {i for _, i in seen} == set(range(30, 60))
    parked_items = sum(1 for wid, _ in seen if wid != 0)
    assert parked_items <= 2, seen  # at most one in-flight grab per parked worker
    pool.stop()
    pool.join()
    assert pool._threads == []


def test_process_pool_shm_slot_config_is_deferred_and_validated():
    from petastorm_tpu.workers.process_pool import ProcessPool
    pool = ProcessPool(2)
    slots, size = pool.set_shm_slot_config(slots_per_worker=7,
                                           slot_bytes=1 << 20)
    assert (slots, size) == (7, 1 << 20)
    assert pool._shm_slots_per_worker == 7  # applies on next ring generation
    with pytest.raises(ValueError):
        pool.set_shm_slot_config(slots_per_worker=0)
    with pytest.raises(ValueError):
        pool.set_shm_slot_config(slot_bytes=16)


def test_shuffling_buffer_threshold_clamps():
    from petastorm_tpu.parallel.shuffling_buffer import RandomShufflingBuffer
    buf = RandomShufflingBuffer(100, 50)
    assert buf.set_min_after_retrieve(10) == 10
    assert buf.min_after_retrieve == 10
    assert buf.set_min_after_retrieve(1000) == 100  # clamped to capacity
    assert buf.set_min_after_retrieve(-5) == 0
    buf.add_many({'x': np.arange(20)})
    buf.set_min_after_retrieve(0)
    assert buf.can_retrieve(20)  # floor lowered mid-stream


def test_cache_bypass_and_writable_hits(tmp_path):
    from petastorm_tpu.cache import ArrowIpcDiskCache
    cache = ArrowIpcDiskCache(str(tmp_path / 'c'), 10 << 20)
    value = {'x': np.arange(8)}
    fills = [0]

    def fill():
        fills[0] += 1
        return value

    cache.get('k', fill)
    hit = cache.get('k', fill)
    assert fills[0] == 1
    assert not hit['x'].flags.writeable  # zero-copy read-only view
    assert cache.set_writable_hits(True) is True
    hit = cache.get('k', fill)
    assert hit['x'].flags.writeable
    assert cache.set_bypass(True) is True
    cache.get('k', fill)
    assert fills[0] == 2  # bypass served a direct fill despite the hot entry
    assert cache.stats['bypass_reads'] == 1
    cache.set_bypass(False)
    cache.get('k', fill)
    assert fills[0] == 2  # hits serve again


def test_decode_threads_knob_gated_to_in_process_pools_and_restores(monkeypatch):
    """Review hardening: decode_threads exists only where decode runs in THIS
    process (thread/dummy pools — process-pool workers captured the env at
    spawn), and its env actuation is undone by restore() so a stopped reader
    cannot leak its tuned width into later readers in the process."""
    from types import SimpleNamespace
    from petastorm_tpu.autotune.knobs import build_reader_knobs
    from petastorm_tpu.workers.thread_pool import ThreadPool
    monkeypatch.delenv('PETASTORM_TPU_DECODE_THREADS', raising=False)
    reader = SimpleNamespace(_ventilator=None, _pool=ThreadPool(2),
                             is_batched_reader=False, _cache=None,
                             _transform_spec=None)
    by_id = {k.knob_id: k for k in build_reader_knobs(reader)}
    knob = by_id['decode_threads']
    assert knob.restore is not None
    # untouched: restore must not stomp state it never changed
    os.environ['PETASTORM_TPU_DECODE_THREADS'] = '7'
    knob.restore()
    assert os.environ['PETASTORM_TPU_DECODE_THREADS'] == '7'
    del os.environ['PETASTORM_TPU_DECODE_THREADS']
    # touched: apply writes the env contract, restore puts the world back
    assert knob.apply(3.0) == 3.0
    assert os.environ['PETASTORM_TPU_DECODE_THREADS'] == '3'
    knob.restore()
    assert 'PETASTORM_TPU_DECODE_THREADS' not in os.environ

    class FakeProcessPool(object):
        workers_count = 2
        _shm_slots_per_worker = 2
        _shm_slot_bytes = 1 << 20

        def set_shm_slot_config(self, **kwargs):
            return (self._shm_slots_per_worker, self._shm_slot_bytes)

    reader = SimpleNamespace(_ventilator=None, _pool=FakeProcessPool(),
                             is_batched_reader=False, _cache=None,
                             _transform_spec=None)
    ids = [k.knob_id for k in build_reader_knobs(reader)]
    assert 'decode_threads' not in ids
    assert 'shm_slots_per_worker' in ids  # the builder still saw the pool


def test_explicit_writable_hits_override_is_pinned_not_a_knob(tmp_path):
    """Review hardening: cache_extra_settings={'writable_hits': ...} is a
    statement about what the consumer needs — the autotuner must not treat
    the hit mode as a free knob on such readers."""
    from types import SimpleNamespace
    from petastorm_tpu.autotune.knobs import build_reader_knobs
    from petastorm_tpu.reader import _make_cache
    pinned = _make_cache('local-disk', str(tmp_path / 'c1'), 10 << 20, 0,
                         {'writable_hits': True})
    assert pinned.writable_hits_pinned is True
    default = _make_cache('local-disk', str(tmp_path / 'c2'), 10 << 20, 0,
                          None)
    assert default.writable_hits_pinned is False

    from petastorm_tpu.workers.thread_pool import ThreadPool

    def knob_ids(cache, pool=None):
        reader = SimpleNamespace(_ventilator=None,
                                 _pool=pool or ThreadPool(1),
                                 is_batched_reader=True, _cache=cache,
                                 _transform_spec=None)
        return [k.knob_id for k in build_reader_knobs(reader)]

    assert 'cache_writable_hits' not in knob_ids(pinned)
    assert 'cache_bypass' in knob_ids(pinned)  # only the hit mode is pinned
    assert 'cache_writable_hits' in knob_ids(default)

    class FakeProcessPool(object):
        workers_count = 2

    # cache knobs are consumer-side objects: a process-pool reader's workers
    # hold their own unpickled cache copies, so no cache knob registers there
    assert knob_ids(default, pool=FakeProcessPool()) == []


def test_scheduler_window_mutators():
    from petastorm_tpu.service.dispatcher import FairShareScheduler
    sched = FairShareScheduler(admission_window=8, clock=lambda: 0.0)
    sched.add_client(b'a', 'a', 'host', window=8)
    sched.add_client(b'b', 'b', 'host', window=4)
    assert sched.effective_client_window() == 4
    assert sched.set_admission_window(6) == 6
    # live clients above the new cap were clamped down, smaller ones kept
    assert {c.window for c in sched._clients.values()} == {6, 4}
    assert sched.set_client_windows(10) == 6  # clamped to the admission cap
    assert {c.window for c in sched._clients.values()} == {6}
    snapshot = sched.autotune_snapshot()
    assert snapshot['counters']['service_busy'] == 0
    assert 'service_queue_depth' in snapshot['gauges']
    # client_window is what accept/busy replies piggyback so live clients
    # adopt retuned windows (unknown client -> the admission cap)
    assert sched.client_window(b'a') == 6
    assert sched.client_window(b'nobody') == 6
    # raising the cap lifts clients UP TO their hello request, never past it
    assert sched.set_admission_window(12) == 12
    assert {c.window for c in sched._clients.values()} == {8, 4}
    # a follow-the-cap client (hello'd windowless) rides the cap both ways
    sched.add_client(b'c', 'c', 'host', window=None)
    assert sched.client_window(b'c') == 12
    sched.set_admission_window(20)
    assert sched.client_window(b'c') == 20
    assert sched.client_window(b'a') == 8  # still pinned to its request


def test_service_pool_learns_window_from_submit_replies():
    """The client adopts the window the dispatcher piggybacks on accept/busy
    replies — dispatcher-side retuning must reach the client's self-pacing,
    else a raised window could never admit more in-flight work."""
    from petastorm_tpu.service.service_client import ServicePool
    pool = object.__new__(ServicePool)
    pool._window = 8
    pool._learn_window(10)
    assert pool._window == 10
    pool._learn_window(6)
    assert pool._window == 6
    pool._learn_window(0)  # zero/absent frames never shrink the window away
    assert pool._window == 6


def test_choose_service_knob_signals():
    from petastorm_tpu.service.dispatcher import choose_service_knob
    sched_knobs = build_service_knobs(_FakeScheduler())
    busy_prev = {'counters': {'service_busy': 0}}
    busy_cur = {'counters': {'service_busy': 3},
                'gauges': {'service_queue_depth': 1.0, 'service_workers': 2.0,
                           'service_admission_window': 16.0,
                           'service_client_window': 8.0}}
    assert choose_service_knob(busy_prev, busy_cur, 0.0,
                               sched_knobs) == 'service_client_window'
    # the common fleet: every client AT the cap (hello'd windowless) — the
    # client-window knob is pinned there, the cap itself is the one to raise
    pinned_cur = {'counters': {'service_busy': 3},
                  'gauges': {'service_queue_depth': 1.0,
                             'service_workers': 2.0,
                             'service_admission_window': 16.0,
                             'service_client_window': 16.0}}
    assert choose_service_knob(busy_prev, pinned_cur, 0.0,
                               sched_knobs) == 'service_admission_window'
    deep_cur = {'counters': {'service_busy': 0},
                'gauges': {'service_queue_depth': 50.0,
                           'service_workers': 2.0}}
    assert choose_service_knob(busy_prev, deep_cur, 0.0,
                               sched_knobs) == 'service_admission_window'
    idle_cur = {'counters': {'service_busy': 0},
                'gauges': {'service_queue_depth': 0.0,
                           'service_workers': 2.0}}
    assert choose_service_knob(busy_prev, idle_cur, 0.0, sched_knobs) is None


class _FakeScheduler(object):
    admission_window = 16

    def set_admission_window(self, value):
        self.admission_window = max(1, value)
        return self.admission_window

    def set_client_windows(self, value):
        return min(value, self.admission_window)

    def effective_client_window(self):
        return self.admission_window


# ------------------------------------------------------- controller units


def test_hill_climb_commits_when_rate_improves():
    pipe = ScriptedPipeline(rate_for=lambda v: 100.0 * v)
    ctl = make_controller(pipe)
    decisions = drive(ctl, pipe, 8)
    actions = [d['action'] for d in decisions]
    assert actions[:2] == ['propose', 'commit']
    assert pipe.value > 1.0
    report = ctl.report()
    assert report['committed'] >= 1
    assert report['knobs']['pool_workers']['value'] == pipe.value


def test_hill_climb_reverts_and_cools_down_without_improvement():
    pipe = ScriptedPipeline(rate_for=lambda v: 100.0)  # knob changes nothing
    ctl = make_controller(pipe)
    decisions = drive(ctl, pipe, 6)
    actions = [d['action'] for d in decisions]
    assert actions[:2] == ['propose', 'revert']
    assert pipe.value == 1.0  # restored
    # cooldown: the next cooldown_windows windows may not re-propose this knob
    more = drive(ctl, pipe, 2)
    assert more == []
    # after cooldown the knob is eligible again, and the failed +1 direction
    # flipped — at the minimum bound the clamp flips it back up, so the knob
    # is re-proposed rather than abandoned (hill-climb keeps exploring)
    more = drive(ctl, pipe, 3)
    assert [d['action'] for d in more][:1] == ['propose']


def test_one_knob_at_a_time_invariant():
    pipe = ScriptedPipeline(rate_for=lambda v: 100.0 * v)
    second = Knob('decode_threads', 'second live knob', minimum=1.0,
                  maximum=8.0, step=1.0, cost='cheap', stages=('pool_wait',),
                  get=lambda: 1.0, apply=lambda v: v)
    ctl = make_controller(pipe)
    ctl.catalog.add(second)
    decisions = drive(ctl, pipe, 12)
    pending = 0
    for decision in decisions:
        if decision['action'] == 'propose':
            assert pending == 0, 'second propose while one was in flight'
            pending = 1
        elif decision['action'] in ('commit', 'revert'):
            pending = 0
    assert any(d['action'] == 'propose' for d in decisions)


def test_warmup_windows_make_no_proposals():
    pipe = ScriptedPipeline(rate_for=lambda v: 100.0 * v)
    ctl = make_controller(pipe, policy=AutotunePolicy(
        window_s=1.0, warmup_windows=4, hold_windows=1))
    assert drive(ctl, pipe, 5) == []  # first sample + 4 warmup windows
    assert [d['action'] for d in drive(ctl, pipe, 1)] == ['propose']


def test_measure_only_policy_never_actuates():
    pipe = ScriptedPipeline(rate_for=lambda v: 100.0 * v)
    ctl = make_controller(pipe, policy=AutotunePolicy(
        window_s=1.0, warmup_windows=0, knob_ids=()))
    assert drive(ctl, pipe, 10) == []
    assert pipe.value == 1.0
    assert ctl.report()['windows'] == 9  # sampled, never turned anything


def test_breaker_interlock_freezes_reverts_and_unfreezes():
    breakers = {'state': {}}
    pipe = ScriptedPipeline(rate_for=lambda v: 100.0 * v)
    ctl = make_controller(pipe, breakers=lambda: breakers['state'])
    decisions = drive(ctl, pipe, 3)  # sample + warmup + propose
    assert [d['action'] for d in decisions] == ['propose']
    assert pipe.value == 2.0  # proposal applied, now held
    breakers['state'] = {'cache:/x': {'state': 'open', 'failures': 5}}
    pipe.tick()
    decision = ctl.step()
    assert decision['action'] == 'freeze'
    assert pipe.value == 1.0  # held proposal was reverted by the interlock
    assert ctl.report()['frozen_by_breaker'] is True
    revert = [d for d in ctl.report()['decisions'] if d['action'] == 'revert']
    assert revert and 'breaker' in revert[0]['reason']
    # while open: frozen, no proposals
    assert drive(ctl, pipe, 3) == []
    breakers['state'] = {}
    unfroze = drive(ctl, pipe, 3)
    assert 'unfreeze' in [d['action'] for d in unfroze]
    assert ctl.report()['frozen_by_breaker'] is False
    # and proposals resume after the freeze cooldown
    assert any(d['action'] == 'propose' for d in drive(ctl, pipe, 6))


def test_no_oscillation_under_noise_gate():
    """Hysteresis: a knob whose effect is below the min_improvement gate is
    reverted and cooled down — the controller must not flip it repeatedly."""
    pipe = ScriptedPipeline(rate_for=lambda v: 100.0 + 0.5 * v)  # ~0.5% gain
    ctl = make_controller(pipe, policy=AutotunePolicy(
        window_s=1.0, warmup_windows=1, hold_windows=1, min_improvement=0.05,
        cooldown_windows=4))
    decisions = drive(ctl, pipe, 20)
    changes = [d for d in decisions if d['action'] in ('propose',)]
    # with a 4-window cooldown after every revert, at most ~1 proposal per 3+4
    # windows fits in 20 — oscillation would show many more
    assert len(changes) <= 4
    assert ctl.report()['committed'] == 0
    assert pipe.value == 1.0


def test_zero_rate_window_never_validates_a_change():
    """Review hardening: a 0 rows/s baseline collapses the hysteresis gate to
    0.0 — a window that measured no progress must not commit (and so teach
    the climb a direction nothing validated)."""
    pipe = ScriptedPipeline(rate_for=lambda v: 0.0)  # consumer fully stalled
    ctl = make_controller(pipe)
    decisions = drive(ctl, pipe, 6)
    actions = [d['action'] for d in decisions]
    assert 'commit' not in actions
    assert 'revert' in actions  # the unmeasured change was rolled back
    assert pipe.value == 1.0


def test_stall_recovery_still_commits():
    """The flip side of the zero-gate guard: 0 -> positive rows/s commits —
    a change that unstuck a stalled pipeline is a real improvement."""
    pipe = ScriptedPipeline(rate_for=lambda v: 0.0 if v <= 1.0 else 200.0)
    ctl = make_controller(pipe)
    decisions = drive(ctl, pipe, 6)
    assert 'commit' in [d['action'] for d in decisions]
    assert pipe.value >= 2.0  # recovered and kept climbing


def test_revert_records_failed_restore_honestly():
    """Review hardening: when the revert's apply raises (dead target), the
    decision must state the LIVE value — the proposed one — not claim a
    rollback that never happened."""
    calls = []

    def flaky_apply(value):
        calls.append(value)
        if len(calls) > 1:
            raise RuntimeError('target torn down')
        return value

    pipe = ScriptedPipeline(rate_for=lambda v: 100.0)  # no improvement
    pipe.knob = Knob('pool_workers', 'flaky target', minimum=1.0,
                     maximum=16.0, step=1.0, cost='cheap',
                     stages=('pool_wait',), get=lambda: 1.0,
                     apply=flaky_apply)
    ctl = make_controller(pipe)
    decisions = drive(ctl, pipe, 6)
    revert = [d for d in decisions if d['action'] == 'revert'][0]
    assert revert['to'] == 2.0  # the live (unrestored) value, not old_value
    assert 'restore FAILED' in revert['reason']


def test_controller_stop_runs_knob_restore_hooks():
    restored = []
    pipe = ScriptedPipeline(rate_for=lambda v: 100.0 * v)
    pipe.knob.restore = lambda: restored.append(True)
    ctl = make_controller(pipe)
    ctl.stop()
    ctl.stop()  # idempotent; hooks must tolerate a second run
    assert restored == [True, True]


def test_scripted_convergence_reaches_fixed_default_rate():
    """The ISSUE-9 convergence criterion, deterministically: rows/s is a known
    concave function of the knob (the fixed default 4 is its plateau); the
    controller starts at the degraded value 1 and must reach >= the
    fixed-default rate within a bounded number of windows."""
    default_rate = 100.0 * 4  # rate_for(fixed default 4)
    pipe = ScriptedPipeline(rate_for=lambda v: 100.0 * min(v, 4.0),
                            initial=1.0, maximum=16.0)
    ctl = make_controller(pipe)
    for window in range(40):
        pipe.tick()
        ctl.step()
        if pipe.rate_for(pipe.value) >= default_rate:
            break
    assert pipe.rate_for(pipe.value) >= default_rate, \
        'did not converge within 40 windows: value={}'.format(pipe.value)
    assert window < 40
    # the climb committed its way up (the final step to 4 may still be a
    # held proposal at break time — the rate criterion above already passed)
    assert ctl.report()['committed'] >= 2


def test_decisions_stream_to_jsonl(tmp_path):
    from petastorm_tpu.telemetry.export import JsonlEventLogger
    path = str(tmp_path / 'decisions.jsonl')
    pipe = ScriptedPipeline(rate_for=lambda v: 100.0 * v)
    ctl = make_controller(pipe, event_logger=JsonlEventLogger(path,
                                                              interval_s=0))
    drive(ctl, pipe, 8)
    with open(path) as f:
        records = [json.loads(line) for line in f]
    assert records, 'no decisions were streamed'
    assert all(r['event'] == 'autotune_decision' for r in records)
    assert records[0]['action'] == 'propose'
    assert records[0]['knob'] == 'pool_workers'


def test_interlock_window_emits_both_decisions_to_jsonl(tmp_path):
    """Decisions are emitted AFTER the controller lock releases (step() may
    record two in one window: the interlock's revert + freeze) — both must
    reach the JSONL stream, in order."""
    from petastorm_tpu.telemetry.export import JsonlEventLogger
    path = str(tmp_path / 'decisions.jsonl')
    breakers = {'state': {}}
    pipe = ScriptedPipeline(rate_for=lambda v: 100.0 * v)
    ctl = make_controller(pipe, breakers=lambda: breakers['state'],
                          event_logger=JsonlEventLogger(path, interval_s=0))
    drive(ctl, pipe, 3)  # sample + warmup + propose (now held)
    breakers['state'] = {'cache:/x': {'state': 'open'}}
    pipe.tick()
    ctl.step()
    with open(path) as f:
        actions = [json.loads(line)['action'] for line in f]
    assert actions == ['propose', 'revert', 'freeze']


def test_decisions_stamp_the_flight_recorder():
    from petastorm_tpu.telemetry import tracing
    tracing.reset_tracing()
    tracing.set_trace_enabled(True)
    try:
        pipe = ScriptedPipeline(rate_for=lambda v: 100.0 * v)
        drive(make_controller(pipe), pipe, 4)
        events = tracing.trace_snapshot().get('events', [])
    finally:
        tracing.set_trace_enabled(False)
        tracing.reset_tracing()
    instants = [e for e in events if e.get('name') == 'autotune_decision']
    assert instants, 'no autotune_decision trace instants recorded'
    assert instants[0]['args']['action'] == 'propose'


def test_snapshot_delta_subtracts_cumulative_series():
    prev = {'histograms': {'decode': {'unit': SECONDS_UNIT, 'count': 10,
                                      'sum': 1.0, 'max': 0.5}},
            'counters': {'service_busy': 2}}
    cur = {'histograms': {'decode': {'unit': SECONDS_UNIT, 'count': 30,
                                     'sum': 4.0, 'max': 0.5},
                          'h2d': {'unit': SECONDS_UNIT, 'count': 5,
                                  'sum': 2.0, 'max': 1.0}},
           'counters': {'service_busy': 7}, 'gauges': {'depth': 3.0}}
    delta = snapshot_delta(prev, cur)
    assert delta['histograms']['decode'] == {'unit': SECONDS_UNIT,
                                             'count': 20, 'sum': 3.0,
                                             'max': 0.5}
    assert delta['histograms']['h2d']['count'] == 5
    assert delta['counters'] == {'service_busy': 5}
    assert delta['gauges'] == {'depth': 3.0}


# ----------------------------------------------------- analyze advisories


def test_analyze_service_advisories():
    from petastorm_tpu.telemetry.analyze import (attribute_bottleneck,
                                                 format_report)
    snapshot = {'histograms': {}, 'counters': {'service_busy': 12},
                'gauges': {'service_queue_depth': 9.0}}
    report = attribute_bottleneck(snapshot)
    signals = {a['signal'] for a in report['advisories']}
    assert signals == {'service_busy', 'service_queue_depth'}
    assert all(a['recommendation'] for a in report['advisories'])
    text = format_report(report)
    assert '[service]' in text and 'service_busy=12' in text


def test_analyze_no_advisories_on_clean_snapshot():
    from petastorm_tpu.telemetry.analyze import attribute_bottleneck
    report = attribute_bottleneck(_stage_snapshot('decode', 2.0))
    assert report['advisories'] == []
    assert report['top_stage'] == 'decode'


# ----------------------------------------------------------------- e2e


@pytest.fixture(scope='module')
def autotune_dataset(tmp_path_factory):
    """A store big enough that epochs outlast control windows (the session
    synthetic dataset is 100 rows — an epoch finishes before one window)."""
    from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_rows
    from petastorm_tpu.unischema import Unischema, UnischemaField
    schema = Unischema('AutotuneBench', [
        UnischemaField('idx', np.int64, (), ScalarCodec(pa.int64()), False),
        UnischemaField('vec', np.float32, (256,), NdarrayCodec(), False),
    ])
    url = 'file://' + str(tmp_path_factory.mktemp('autotune') / 'dataset')
    write_rows(url, schema,
               ({'idx': i, 'vec': np.full(256, i % 97, np.float32)}
                for i in range(8000)), rowgroup_size_mb=1)
    return url


@pytest.fixture(autouse=True)
def _restore_decode_threads_env():
    saved = os.environ.get('PETASTORM_TPU_DECODE_THREADS')
    yield
    if saved is None:
        os.environ.pop('PETASTORM_TPU_DECODE_THREADS', None)
    else:
        os.environ['PETASTORM_TPU_DECODE_THREADS'] = saved


def test_reader_autotune_off_is_inert(synthetic_dataset):
    from petastorm_tpu import make_reader
    with make_reader(synthetic_dataset.url, workers_count=2,
                     num_epochs=1) as reader:
        assert reader._autotune is None
        assert reader.autotune_report() == {'enabled': False}
        assert 'autotune' not in reader.diagnostics
        before = (reader._pool.workers_count,
                  reader._ventilator.max_in_flight)
        rows = sum(batch.num_rows for batch in reader.iter_columnar())
        assert rows == len(synthetic_dataset.rows)
        # no knob mutated when disabled — the seed path byte-identical
        assert (reader._pool.workers_count,
                reader._ventilator.max_in_flight) == before


def test_reader_autotune_converges_from_degraded_defaults(autotune_dataset):
    """ISSUE-9 e2e: a reader started with deliberately bad knobs (1 worker,
    in-flight window 1) and an aggressive policy commits at least one
    improvement within a bounded number of windows, mid-epoch, while rows
    keep flowing correctly."""
    from petastorm_tpu import make_reader
    policy = AutotunePolicy(window_s=0.15, warmup_windows=1, hold_windows=1,
                            min_improvement=0.005, cooldown_windows=2)
    reader = make_reader(autotune_dataset, workers_count=1, num_epochs=None,
                         autotune=policy)
    try:
        reader._ventilator.set_max_in_flight(1)
        rows = 0
        deadline = time.time() + 30
        report = reader.autotune_report()
        for batch in reader.iter_columnar():
            assert np.all(batch.columns['vec'][:, 0]
                          == batch.columns['idx'] % 97)
            rows += batch.num_rows
            report = reader.autotune_report()
            if report['committed'] >= 1 or time.time() > deadline:
                break
        assert report['enabled']
        assert report['committed'] >= 1, report['decisions']
        # the hill climb moved a degraded knob upward from its floor
        knobs = report['knobs']
        assert (knobs['pool_workers']['value'] > 1
                or knobs['ventilator_max_in_flight']['value'] > 1)
        assert rows > 0
        assert 'autotune' in reader.diagnostics
        assert not report['frozen_by_breaker']
    finally:
        reader.stop()
        reader.join()


def test_reader_autotune_knob_catalog_shape(autotune_dataset):
    """The reader builds the documented knob set for a thread-pool decoding
    reader (docs/autotuning.md knob table)."""
    from petastorm_tpu import make_reader
    reader = make_reader(autotune_dataset, workers_count=2, num_epochs=1,
                         autotune=AutotunePolicy(window_s=3600.0))
    try:
        knobs = reader.autotune_report()['knobs']
        assert set(knobs) == {'ventilator_max_in_flight', 'pool_workers',
                              'decode_threads'}
        for entry in knobs.values():
            assert entry['min'] <= entry['value'] <= entry['max']
    finally:
        reader.stop()
        reader.join()


def test_loader_registers_shuffle_buffer_knob(autotune_dataset):
    from petastorm_tpu import make_reader
    from petastorm_tpu.parallel.loader import JaxDataLoader
    reader = make_reader(autotune_dataset, workers_count=1, num_epochs=1,
                         autotune=AutotunePolicy(window_s=3600.0))
    try:
        loader = JaxDataLoader(reader, batch_size=32, device_put=False,
                               shuffling_queue_capacity=256, seed=1)
        catalog = reader._autotune.catalog
        assert 'loader_min_after_retrieve' in catalog
        knob = catalog.knob('loader_min_after_retrieve')
        assert knob.get() == 128.0  # capacity // 2 default resolved
        assert knob.apply(32.0) == 32.0
        assert loader._min_after_retrieve == 32
        it = iter(loader)
        first = next(it)
        assert first  # the live buffer picks up further turns
        assert knob.apply(0.0) == 0.0
        assert loader._active_buffer.min_after_retrieve == 0
        loader.stop()
        loader.join()
    finally:
        reader.stop()
        reader.join()


def test_loader_without_autotune_registers_nothing(autotune_dataset):
    from petastorm_tpu import make_reader
    from petastorm_tpu.parallel.loader import JaxDataLoader
    with make_reader(autotune_dataset, workers_count=1,
                     num_epochs=1) as reader:
        loader = JaxDataLoader(reader, batch_size=32, device_put=False)
        assert loader._active_buffer is None
        assert build_loader_knobs(loader) == []  # no shuffling buffer knob


def test_dispatcher_autotune_state_block():
    from petastorm_tpu.service.dispatcher import Dispatcher
    dispatcher = Dispatcher(autotune=AutotunePolicy(window_s=3600.0))
    try:
        dispatcher.start()
        state = dispatcher.state()
        assert state['autotune']['enabled']
        assert set(state['autotune']['knobs']) == {'service_admission_window',
                                                   'service_client_window'}
    finally:
        dispatcher.stop()
        dispatcher.join()


def test_dispatcher_without_autotune_has_no_block():
    from petastorm_tpu.service.dispatcher import Dispatcher
    dispatcher = Dispatcher()
    assert dispatcher._autotune is None
    assert 'autotune' not in dispatcher.state()
