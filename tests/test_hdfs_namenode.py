"""Dedicated HDFS namenode-resolution + HA-failover tests with programmable mock
connectors (model: reference petastorm/hdfs/tests/test_hdfs_namenode.py:42,265,309 —
resolver matrix, env-var conf discovery, connect failover counts, HA client behavior).
No HDFS cluster is ever touched: connections are mocks with scripted failure counts.
"""
import os
import pickle

import pytest

from petastorm_tpu.fs_utils import _resolve_hdfs
from petastorm_tpu.hdfs.namenode import (
    HAHdfsClient, HdfsConfigError, HdfsConnectError, HdfsConnector,
    HdfsNamenodeResolver, namenode_failover)

HA_CONFIG = {
    'fs.defaultFS': 'hdfs://nameservice1',
    'dfs.nameservices': 'nameservice1,ns2',
    'dfs.ha.namenodes.nameservice1': 'nn1,nn2',
    'dfs.namenode.rpc-address.nameservice1.nn1': 'nn1.example.com:8020',
    'dfs.namenode.rpc-address.nameservice1.nn2': 'nn2.example.com:8020',
    'dfs.ha.namenodes.ns2': 'a,b,c',
    'dfs.namenode.rpc-address.ns2.a': 'a:8020',
    'dfs.namenode.rpc-address.ns2.b': 'b:8020',
    'dfs.namenode.rpc-address.ns2.c': 'c:8020',
}


class MockHdfs(object):
    """Filesystem stand-in whose operations fail for the first ``n_failovers`` calls
    (model: reference MockHdfs, test_hdfs_namenode.py:265-306)."""

    def __init__(self, n_failovers=0):
        self.n_failovers = n_failovers
        self.calls = 0

    def get_file_info(self, path):
        self.calls += 1
        if self.n_failovers > 0:
            self.n_failovers -= 1
            raise OSError('scripted failure ({} left)'.format(self.n_failovers))
        return 'info:{}'.format(path)

    @property
    def type_name(self):
        return 'mockhdfs'


class MockHdfsConnector(HdfsConnector):
    """Connector whose namenode connections fail a scripted number of times per
    address (model: reference MockHdfsConnector, test_hdfs_namenode.py:309-355)."""

    _fail_n_next_connect = {}
    connect_attempts = []

    @classmethod
    def reset(cls):
        cls._fail_n_next_connect = {}
        cls.connect_attempts = []

    @classmethod
    def set_fail_n_next_connect(cls, address, count):
        cls._fail_n_next_connect[address] = count

    @classmethod
    def hdfs_connect_namenode(cls, address, user=None):
        cls.connect_attempts.append((address, user))
        remaining = cls._fail_n_next_connect.get(address, 0)
        if remaining > 0:
            cls._fail_n_next_connect[address] = remaining - 1
            raise IOError('namenode {} down'.format(address))
        return MockHdfs()


@pytest.fixture(autouse=True)
def _reset_mock_connector():
    MockHdfsConnector.reset()
    yield
    MockHdfsConnector.reset()


class TestResolverDefaultService:
    def test_typical_ha_default(self):
        service, namenodes = HdfsNamenodeResolver(HA_CONFIG).resolve_default_hdfs_service()
        assert service == 'nameservice1'
        assert namenodes == ['nn1.example.com:8020', 'nn2.example.com:8020']

    def test_missing_default_fs(self):
        with pytest.raises(HdfsConfigError):
            HdfsNamenodeResolver({}).resolve_default_hdfs_service()

    def test_non_hdfs_default_fs(self):
        config = {'fs.defaultFS': 'file:///tmp'}
        with pytest.raises(HdfsConfigError):
            HdfsNamenodeResolver(config).resolve_default_hdfs_service()

    def test_default_fs_with_path_suffix(self):
        config = dict(HA_CONFIG, **{'fs.defaultFS': 'hdfs://nameservice1/user/me'})
        service, namenodes = HdfsNamenodeResolver(config).resolve_default_hdfs_service()
        assert service == 'nameservice1'
        assert len(namenodes) == 2


class TestResolverNameService:
    def test_ha_pair(self):
        resolver = HdfsNamenodeResolver(HA_CONFIG)
        assert resolver.resolve_hdfs_name_service('nameservice1') == \
            ['nn1.example.com:8020', 'nn2.example.com:8020']

    def test_more_than_max_namenodes_truncated(self):
        resolver = HdfsNamenodeResolver(HA_CONFIG)
        assert resolver.resolve_hdfs_name_service('ns2') == ['a:8020', 'b:8020']

    def test_unknown_service_is_direct_host(self):
        resolver = HdfsNamenodeResolver(HA_CONFIG)
        assert resolver.resolve_hdfs_name_service('plainhost:9000') == ['plainhost:9000']

    def test_empty_nameservice_raises(self):
        with pytest.raises(HdfsConfigError):
            HdfsNamenodeResolver(HA_CONFIG).resolve_hdfs_name_service('')

    def test_declared_service_without_namenode_list_raises(self):
        config = dict(HA_CONFIG)
        del config['dfs.ha.namenodes.nameservice1']
        with pytest.raises(HdfsConfigError):
            HdfsNamenodeResolver(config).resolve_hdfs_name_service('nameservice1')

    def test_declared_service_missing_rpc_address_raises(self):
        config = dict(HA_CONFIG)
        del config['dfs.namenode.rpc-address.nameservice1.nn2']
        with pytest.raises(HdfsConfigError):
            HdfsNamenodeResolver(config).resolve_hdfs_name_service('nameservice1')


def _write_hadoop_conf(home, core_site=None, hdfs_site=None):
    conf_dir = os.path.join(str(home), 'etc', 'hadoop')
    os.makedirs(conf_dir, exist_ok=True)

    def write(file_name, properties):
        body = ''.join(
            '<property><name>{}</name><value>{}</value></property>'.format(k, v)
            for k, v in properties.items())
        with open(os.path.join(conf_dir, file_name), 'w') as f:
            f.write('<configuration>{}</configuration>'.format(body))

    if core_site is not None:
        write('core-site.xml', core_site)
    if hdfs_site is not None:
        write('hdfs-site.xml', hdfs_site)


class TestEnvConfigDiscovery:
    """Hadoop conf located via HADOOP_HOME / HADOOP_PREFIX / HADOOP_INSTALL (model:
    reference test_hdfs_namenode.py:201-259)."""

    CORE = {'fs.defaultFS': 'hdfs://envservice'}
    HDFS = {
        'dfs.nameservices': 'envservice',
        'dfs.ha.namenodes.envservice': 'nn1,nn2',
        'dfs.namenode.rpc-address.envservice.nn1': 'env1:8020',
        'dfs.namenode.rpc-address.envservice.nn2': 'env2:8020',
    }

    @pytest.fixture(autouse=True)
    def _clean_env(self, monkeypatch):
        for var in ('HADOOP_HOME', 'HADOOP_PREFIX', 'HADOOP_INSTALL',
                    'HADOOP_CONF_DIR'):
            monkeypatch.delenv(var, raising=False)

    @pytest.mark.parametrize('var', ['HADOOP_HOME', 'HADOOP_PREFIX', 'HADOOP_INSTALL'])
    def test_each_env_var_is_honored(self, tmp_path, monkeypatch, var):
        _write_hadoop_conf(tmp_path, core_site=self.CORE, hdfs_site=self.HDFS)
        monkeypatch.setenv(var, str(tmp_path))
        service, namenodes = HdfsNamenodeResolver().resolve_default_hdfs_service()
        assert service == 'envservice'
        assert namenodes == ['env1:8020', 'env2:8020']

    def test_hadoop_conf_dir_points_at_conf_directly(self, tmp_path, monkeypatch):
        conf_dir = tmp_path / 'conf-only'
        _write_hadoop_conf(conf_dir, core_site=self.CORE, hdfs_site=self.HDFS)
        monkeypatch.setenv('HADOOP_CONF_DIR',
                           str(conf_dir / 'etc' / 'hadoop'))
        service, _ = HdfsNamenodeResolver().resolve_default_hdfs_service()
        assert service == 'envservice'

    def test_hadoop_conf_dir_wins_over_hadoop_home(self, tmp_path, monkeypatch):
        primary = tmp_path / 'primary'
        other = tmp_path / 'other'
        _write_hadoop_conf(primary, core_site=self.CORE, hdfs_site=self.HDFS)
        _write_hadoop_conf(other, core_site={'fs.defaultFS': 'hdfs://otherservice'})
        monkeypatch.setenv('HADOOP_CONF_DIR', str(primary / 'etc' / 'hadoop'))
        monkeypatch.setenv('HADOOP_HOME', str(other))
        service, _ = HdfsNamenodeResolver().resolve_default_hdfs_service()
        assert service == 'envservice'

    def test_first_populated_var_wins(self, tmp_path, monkeypatch):
        good = tmp_path / 'good'
        other = tmp_path / 'other'
        _write_hadoop_conf(good, core_site=self.CORE, hdfs_site=self.HDFS)
        _write_hadoop_conf(other, core_site={'fs.defaultFS': 'hdfs://otherservice'})
        monkeypatch.setenv('HADOOP_HOME', str(good))
        monkeypatch.setenv('HADOOP_INSTALL', str(other))
        service, _ = HdfsNamenodeResolver().resolve_default_hdfs_service()
        assert service == 'envservice'

    def test_bad_home_falls_through_to_next_var(self, tmp_path, monkeypatch):
        _write_hadoop_conf(tmp_path, core_site=self.CORE, hdfs_site=self.HDFS)
        monkeypatch.setenv('HADOOP_HOME', str(tmp_path / 'does-not-exist'))
        monkeypatch.setenv('HADOOP_INSTALL', str(tmp_path))
        service, _ = HdfsNamenodeResolver().resolve_default_hdfs_service()
        assert service == 'envservice'

    def test_no_conf_files_yields_empty_config(self, tmp_path, monkeypatch):
        monkeypatch.setenv('HADOOP_HOME', str(tmp_path))
        with pytest.raises(HdfsConfigError):
            HdfsNamenodeResolver().resolve_default_hdfs_service()

    def test_hdfs_site_only(self, tmp_path, monkeypatch):
        _write_hadoop_conf(tmp_path, hdfs_site=dict(self.HDFS, **self.CORE))
        monkeypatch.setenv('HADOOP_HOME', str(tmp_path))
        service, namenodes = HdfsNamenodeResolver().resolve_default_hdfs_service()
        assert service == 'envservice'
        assert len(namenodes) == 2


class TestConnectFailover:
    """connect_to_either_namenode retry/failover accounting (model: reference
    test_hdfs_namenode.py:370-419)."""

    NODES = ['nn1:8020', 'nn2:8020']

    def test_first_namenode_ok(self):
        fs = MockHdfsConnector.connect_to_either_namenode(self.NODES)
        assert isinstance(fs, MockHdfs)
        assert MockHdfsConnector.connect_attempts == [('nn1:8020', None)]

    def test_user_is_threaded_through(self):
        MockHdfsConnector.connect_to_either_namenode(self.NODES, user='alice')
        assert MockHdfsConnector.connect_attempts == [('nn1:8020', 'alice')]

    def test_one_failure_retries_same_namenode(self):
        MockHdfsConnector.set_fail_n_next_connect('nn1:8020', 1)
        fs = MockHdfsConnector.connect_to_either_namenode(self.NODES)
        assert isinstance(fs, MockHdfs)
        addresses = [a for a, _ in MockHdfsConnector.connect_attempts]
        assert addresses == ['nn1:8020', 'nn1:8020']

    def test_two_failures_fail_over_to_second(self):
        MockHdfsConnector.set_fail_n_next_connect('nn1:8020', 2)
        fs = MockHdfsConnector.connect_to_either_namenode(self.NODES)
        assert isinstance(fs, MockHdfs)
        addresses = [a for a, _ in MockHdfsConnector.connect_attempts]
        assert addresses == ['nn1:8020', 'nn1:8020', 'nn2:8020']

    def test_four_failures_raise(self):
        MockHdfsConnector.set_fail_n_next_connect('nn1:8020', 2)
        MockHdfsConnector.set_fail_n_next_connect('nn2:8020', 2)
        with pytest.raises(HdfsConnectError):
            MockHdfsConnector.connect_to_either_namenode(self.NODES)
        assert len(MockHdfsConnector.connect_attempts) == 4


class TestTryNextNamenode:
    def test_round_robin_from_fresh(self):
        idx, fs = MockHdfsConnector._try_next_namenode(-1, ['a:1', 'b:2'])
        assert idx == 0 and isinstance(fs, MockHdfs)

    def test_round_robin_advances_past_current(self):
        MockHdfsConnector.set_fail_n_next_connect('b:2', 1)
        idx, _ = MockHdfsConnector._try_next_namenode(0, ['a:1', 'b:2'])
        # b (next after a) fails once, wraps around to a.
        assert idx == 0
        addresses = [a for a, _ in MockHdfsConnector.connect_attempts]
        assert addresses == ['b:2', 'a:1']

    def test_all_down_raises(self):
        MockHdfsConnector.set_fail_n_next_connect('a:1', 5)
        MockHdfsConnector.set_fail_n_next_connect('b:2', 5)
        with pytest.raises(HdfsConnectError):
            MockHdfsConnector._try_next_namenode(-1, ['a:1', 'b:2'])


class TestHAHdfsClient:
    """HA proxy semantics (model: reference HAHdfsClientTest,
    test_hdfs_namenode.py:422-539)."""

    NODES = ['nn1:8020', 'nn2:8020']

    def test_connect_ha_returns_proxy(self):
        client = MockHdfsConnector.connect_ha(self.NODES)
        assert isinstance(client, HAHdfsClient)
        assert isinstance(client.unwrap(), MockHdfs)

    def test_empty_namenode_list_raises(self):
        with pytest.raises(HdfsConnectError):
            MockHdfsConnector.connect_ha([])

    def test_operation_passthrough(self):
        client = MockHdfsConnector.connect_ha(self.NODES)
        assert client.get_file_info('/x') == 'info:/x'

    def test_non_callable_attribute_passthrough(self):
        client = MockHdfsConnector.connect_ha(self.NODES)
        assert client.type_name == 'mockhdfs'

    def test_operation_failover_reconnects_to_next_namenode(self):
        client = MockHdfsConnector.connect_ha(self.NODES)
        client.unwrap().n_failovers = 1
        first_fs = client.unwrap()
        assert client.get_file_info('/x') == 'info:/x'
        assert client.unwrap() is not first_fs
        addresses = [a for a, _ in MockHdfsConnector.connect_attempts]
        assert addresses == ['nn1:8020', 'nn2:8020']

    def test_two_consecutive_failures_propagate(self):
        client = MockHdfsConnector.connect_ha(self.NODES)

        class AlwaysDown(MockHdfs):
            def get_file_info(self, path):
                raise OSError('down forever')

        client._filesystem = AlwaysDown()
        original_connect = MockHdfsConnector.hdfs_connect_namenode
        try:
            MockHdfsConnector.hdfs_connect_namenode = classmethod(
                lambda cls, address, user=None: AlwaysDown())
            with pytest.raises(OSError):
                client.get_file_info('/x')
        finally:
            MockHdfsConnector.hdfs_connect_namenode = original_connect

    def test_file_semantic_oserror_is_not_failed_over(self):
        # FileNotFoundError describes the file, not the connection: no reconnect,
        # no duplicate attempt.
        client = MockHdfsConnector.connect_ha(self.NODES)

        class MissingFs(MockHdfs):
            def get_file_info(self, path):
                self.calls += 1
                raise FileNotFoundError(path)

        fs = MissingFs()
        client._filesystem = fs
        with pytest.raises(FileNotFoundError):
            client.get_file_info('/gone')
        assert fs.calls == 1
        assert len(MockHdfsConnector.connect_attempts) == 1  # only the initial connect

    def test_unhandled_exception_is_not_retried(self):
        client = MockHdfsConnector.connect_ha(self.NODES)

        class TypeErrorFs(MockHdfs):
            def get_file_info(self, path):
                self.calls += 1
                raise TypeError('not an OSError')

        broken = TypeErrorFs()
        client._filesystem = broken
        with pytest.raises(TypeError):
            client.get_file_info('/x')
        assert broken.calls == 1

    def test_client_pickles_correctly(self):
        client = MockHdfsConnector.connect_ha(self.NODES, user='bob')
        restored = pickle.loads(pickle.dumps(client))
        assert isinstance(restored, HAHdfsClient)
        assert restored._namenode_addresses == self.NODES
        assert restored._user == 'bob'
        assert restored.get_file_info('/y') == 'info:/y'

    def test_private_attribute_access_raises(self):
        client = MockHdfsConnector.connect_ha(self.NODES)
        with pytest.raises(AttributeError):
            client._does_not_exist  # noqa: B018


class TestArrowUnwrap:
    def test_plain_filesystem_passthrough(self):
        from petastorm_tpu.fs_utils import as_arrow_filesystem
        sentinel = object()
        assert as_arrow_filesystem(sentinel) is sentinel

    def test_ha_proxy_unwraps_to_live_connection(self):
        from petastorm_tpu.fs_utils import as_arrow_filesystem
        client = MockHdfsConnector.connect_ha(['nn1:8020', 'nn2:8020'])
        assert as_arrow_filesystem(client) is client.unwrap()


class TestProxyThroughReaderStack:
    def test_make_reader_accepts_ha_proxy_filesystem(self, tmp_path):
        """A resolver that yields the HA proxy must still read end-to-end: the Arrow
        C++ hand-offs (pads.dataset, worker make_fragment) unwrap it (regression:
        the proxy is a plain python object pyarrow rejects)."""
        import pyarrow.fs as pafs

        import numpy as np
        from petastorm_tpu.codecs import ScalarCodec
        from petastorm_tpu.etl.dataset_metadata import write_rows
        from petastorm_tpu.reader import make_reader
        from petastorm_tpu.unischema import Unischema, UnischemaField

        schema = Unischema('S', [
            UnischemaField('id', np.int64, (), ScalarCodec(np.int64), False)])
        url = 'file://' + str(tmp_path / 'ds')
        write_rows(url, schema, [{'id': i} for i in range(10)], rows_per_file=5)

        class LocalConnector(HdfsConnector):
            @classmethod
            def hdfs_connect_namenode(cls, address, user=None):
                return pafs.LocalFileSystem()

        proxy = LocalConnector.connect_ha(['nn1:8020', 'nn2:8020'])
        with make_reader(url, reader_pool_type='dummy', filesystem=proxy,
                         shuffle_row_groups=False) as reader:
            ids = [row.id for row in reader]
        assert sorted(ids) == list(range(10))


class TestNamenodeFailoverDecorator:
    def test_retries_once_with_reconnect(self):
        class Client:
            def __init__(self):
                self.reconnects = 0
                self.attempts = 0

            def reconnect(self):
                self.reconnects += 1

            @namenode_failover
            def op(self):
                self.attempts += 1
                if self.attempts == 1:
                    raise OSError('transient')
                return 'ok'

        client = Client()
        assert client.op() == 'ok'
        assert client.reconnects == 1

    def test_second_failure_propagates(self):
        class Client:
            @namenode_failover
            def op(self):
                raise OSError('hard down')

        with pytest.raises(OSError):
            Client().op()

    def test_file_not_found_is_not_retried(self):
        class Client:
            attempts = 0

            @namenode_failover
            def op(self):
                Client.attempts += 1
                raise FileNotFoundError('/gone')

        with pytest.raises(FileNotFoundError):
            Client().op()
        assert Client.attempts == 1


class TestFsUtilsHdfsRouting:
    """_resolve_hdfs dispatch: host:port direct, nameservice via failover, hostless via
    fs.defaultFS (reference: petastorm/fs_utils.py:82-130)."""

    @pytest.fixture(autouse=True)
    def _conf_env(self, tmp_path, monkeypatch):
        _write_hadoop_conf(
            tmp_path,
            core_site={'fs.defaultFS': 'hdfs://routed'},
            hdfs_site={
                'dfs.nameservices': 'routed',
                'dfs.ha.namenodes.routed': 'nn1,nn2',
                'dfs.namenode.rpc-address.routed.nn1': 'r1:8020',
                'dfs.namenode.rpc-address.routed.nn2': 'r2:8020',
            })
        for var in ('HADOOP_PREFIX', 'HADOOP_INSTALL', 'HADOOP_CONF_DIR'):
            monkeypatch.delenv(var, raising=False)
        monkeypatch.setenv('HADOOP_HOME', str(tmp_path))

    @pytest.fixture(autouse=True)
    def _capture_connections(self, monkeypatch):
        self.direct = []
        self.single = []
        self.ha = []

        import pyarrow.fs as pafs

        def fake_direct(host, port, user=None, **kwargs):
            self.direct.append((host, port))
            return 'direct-fs'

        monkeypatch.setattr(pafs, 'HadoopFileSystem', fake_direct)
        monkeypatch.setattr(
            HdfsConnector, 'connect_to_either_namenode',
            classmethod(lambda cls, nodes, user=None:
                        self.single.append(list(nodes)) or 'single-fs'))
        monkeypatch.setattr(
            HdfsConnector, 'connect_ha',
            classmethod(lambda cls, nodes, user=None:
                        self.ha.append(list(nodes)) or 'ha-proxy'))

    def test_host_port_connects_directly(self):
        assert _resolve_hdfs('hdfs://somehost:9000/ds') == 'direct-fs'
        assert self.direct == [('somehost', 9000)]
        assert self.single == [] and self.ha == []

    def test_nameservice_routes_through_ha_proxy(self):
        # Multi-namenode resolutions get the operation-level failover proxy.
        assert _resolve_hdfs('hdfs://routed/ds') == 'ha-proxy'
        assert self.ha == [['r1:8020', 'r2:8020']]
        assert self.single == []

    def test_hostless_uses_default_fs(self):
        assert _resolve_hdfs('hdfs:///ds') == 'ha-proxy'
        assert self.ha == [['r1:8020', 'r2:8020']]

    def test_portless_unknown_host_is_single_namenode(self):
        assert _resolve_hdfs('hdfs://lonehost/ds') == 'single-fs'
        assert self.single == [['lonehost']]
        assert self.ha == []

    def test_no_hadoop_config_falls_back_to_libhdfs_default(self, monkeypatch):
        # Port 0 lets libhdfs do its own core-site.xml / logical-nameservice lookup.
        monkeypatch.setenv('HADOOP_HOME', '/nonexistent-hadoop')
        assert _resolve_hdfs('hdfs:///ds') == 'direct-fs'
        assert self.direct == [('default', 0)]

    def test_no_hadoop_config_hands_portless_authority_to_libhdfs(self, monkeypatch):
        # With NO local hadoop config, a portless authority may be a logical HA
        # nameservice only libhdfs's own config can resolve — it must go to libhdfs
        # with port 0, not direct-connect to <authority>:8020 (ADVICE round 2).
        monkeypatch.setenv('HADOOP_HOME', '/nonexistent-hadoop')
        assert _resolve_hdfs('hdfs://logicalns/ds') == 'direct-fs'
        assert self.direct == [('logicalns', 0)]
        assert self.single == [] and self.ha == []
