"""Vectorized decode engine tests (ISSUE 7): compiled plan kernels on adversarial
Arrow layouts (sliced/offset chunks, nulls, ragged shapes, non-native endianness),
predicate pushdown vs per-row Python equivalence, the single-read two-phase path,
and the TransformSpec vectorized pre-pass."""

import numpy as np
import pyarrow as pa
import pytest

from petastorm_tpu import decode_engine, make_reader
from petastorm_tpu.codecs import (CompressedImageCodec, CompressedNdarrayCodec,
                                  FieldCodec, NdarrayCodec, ScalarCodec)
from petastorm_tpu.predicates import (in_lambda, in_negate,
                                      in_pseudorandom_split, in_reduce, in_set)
from petastorm_tpu.transform import TransformSpec
from petastorm_tpu.unischema import Unischema, UnischemaField

# ------------------------------------------------------------- codec kernels


def _per_cell_reference(field, arrow_col):
    """The pre-engine worker behavior: python cells, per-cell decode dispatch."""
    return FieldCodec.decode_column(field.codec, field, arrow_col.to_pylist())


def _assert_columns_equal(actual, expected):
    if isinstance(actual, np.ndarray) and isinstance(expected, np.ndarray):
        np.testing.assert_array_equal(actual, expected)
        return
    actual = list(actual)
    expected = list(expected)
    assert len(actual) == len(expected)
    for a, e in zip(actual, expected):
        if e is None:
            assert a is None
        else:
            np.testing.assert_array_equal(np.asarray(a), np.asarray(e))


def _encoded_column(field, values, arrow_type=pa.binary()):
    return pa.chunked_array([pa.array(
        [None if v is None else field.codec.encode(field, v) for v in values],
        type=arrow_type)])


CODEC_CASES = [
    ('ndarray', NdarrayCodec(), np.float32, (5, 3)),
    ('compressed_ndarray', CompressedNdarrayCodec(), np.float32, (5, 3)),
    ('image_png', CompressedImageCodec('png'), np.uint8, (8, 6, 3)),
]


def _codec_values(dtype, shape, n=7, seed=3):
    rng = np.random.RandomState(seed)
    if np.dtype(dtype) == np.uint8:
        return [rng.randint(0, 255, shape).astype(dtype) for _ in range(n)]
    return [rng.rand(*shape).astype(dtype) for _ in range(n)]


@pytest.mark.parametrize('name,codec,dtype,shape', CODEC_CASES)
def test_sliced_offset_chunks_decode_identically(name, codec, dtype, shape):
    """A sliced chunk's buffer offsets must not shift the decoded payloads."""
    field = UnischemaField('x', dtype, shape, codec, False)
    values = _codec_values(dtype, shape, n=9)
    col = _encoded_column(field, values)
    sliced = pa.chunked_array([col.chunk(0).slice(2, 5)])
    out = codec.decode_arrow_column(field, sliced)
    _assert_columns_equal(out, _per_cell_reference(field, sliced))
    _assert_columns_equal(out, [codec.decode(field, field.codec.encode(field, v))
                                for v in values[2:7]])


@pytest.mark.parametrize('name,codec,dtype,shape', CODEC_CASES)
def test_null_containing_chunks_keep_none_cells(name, codec, dtype, shape):
    field = UnischemaField('x', dtype, shape, codec, True)
    values = _codec_values(dtype, shape, n=5)
    values[1] = None
    values[4] = None
    col = _encoded_column(field, values)
    out = codec.decode_arrow_column(field, col)
    assert isinstance(out, list)
    _assert_columns_equal(out, _per_cell_reference(field, col))


@pytest.mark.parametrize('name,codec,dtype', [
    ('ndarray', NdarrayCodec(), np.float32),
    ('compressed_ndarray', CompressedNdarrayCodec(), np.float32),
])
def test_ragged_shapes_demote_to_lists(name, codec, dtype):
    field = UnischemaField('x', dtype, (None, None), codec, False)
    rng = np.random.RandomState(0)
    values = [rng.rand(2, 3).astype(dtype), rng.rand(2, 3).astype(dtype),
              rng.rand(4, 1).astype(dtype)]
    out = codec.decode_arrow_column(field, _encoded_column(field, values))
    assert isinstance(out, list)
    for a, e in zip(out, values):
        np.testing.assert_array_equal(np.asarray(a), e)


@pytest.mark.parametrize('name,codec', [
    ('ndarray', NdarrayCodec()),
    ('compressed_ndarray', CompressedNdarrayCodec()),
])
def test_non_native_endian_dtypes(name, codec):
    """Big-endian payloads must decode with their declared byte order intact."""
    be = np.dtype('>f4')
    field = UnischemaField('x', be, (3, 3), codec, False)
    rng = np.random.RandomState(1)
    values = [rng.rand(3, 3).astype(be) for _ in range(4)]
    out = codec.decode_arrow_column(field, _encoded_column(field, values))
    stacked = np.asarray(out) if isinstance(out, np.ndarray) else np.stack(
        [np.asarray(v) for v in out])
    np.testing.assert_array_equal(stacked, np.stack(values))


def test_mixed_uniform_then_ragged_chunk_demotes_cleanly():
    """The preallocated fast path must demote mid-column without losing the
    already-decoded prefix."""
    codec = CompressedNdarrayCodec()
    field = UnischemaField('x', np.float32, (None, None), codec, False)
    rng = np.random.RandomState(2)
    values = [rng.rand(2, 2).astype(np.float32) for _ in range(3)]
    values.append(rng.rand(5, 5).astype(np.float32))
    out = codec.decode_arrow_column(field, _encoded_column(field, values))
    assert isinstance(out, list) and len(out) == 4
    for a, e in zip(out, values):
        np.testing.assert_array_equal(np.asarray(a), e)


def test_compressed_ndarray_engine_output_is_writable():
    codec = CompressedNdarrayCodec()
    field = UnischemaField('x', np.float32, (2, 2), codec, False)
    values = _codec_values(np.float32, (2, 2), n=3)
    out = codec.decode_arrow_column(field, _encoded_column(field, values))
    assert isinstance(out, np.ndarray) and out.flags.writeable
    cells = codec.decode_column(field, [field.codec.encode(field, v)
                                        for v in values])
    assert all(c.flags.writeable for c in cells)


def test_image_decode_thread_fanout_matches_serial(monkeypatch):
    """The threaded image kernel must be bit-identical to the serial one."""
    codec = CompressedImageCodec('png')
    field = UnischemaField('img', np.uint8, (8, 6, 3), codec, False)
    values = _codec_values(np.uint8, (8, 6, 3), n=24)
    col = _encoded_column(field, values)
    monkeypatch.setenv('PETASTORM_TPU_DECODE_THREADS', '1')
    serial = codec.decode_arrow_column(field, col)
    monkeypatch.setenv('PETASTORM_TPU_DECODE_THREADS', '3')
    threaded = codec.decode_arrow_column(field, col)
    assert isinstance(serial, np.ndarray) and isinstance(threaded, np.ndarray)
    np.testing.assert_array_equal(serial, threaded)


# ------------------------------------------------------------ decode plans


def _scalar_schema():
    return Unischema('PlanSchema', [
        UnischemaField('id', np.int64, (), ScalarCodec(), False),
        UnischemaField('name', np.str_, (), ScalarCodec(), False),
        UnischemaField('vec', np.float32, (2, 2), NdarrayCodec(), False),
    ])


def _scalar_table(n=10):
    schema = _scalar_schema()
    rng = np.random.RandomState(0)
    vecs = [rng.rand(2, 2).astype(np.float32) for _ in range(n)]
    table = pa.table({
        'id': pa.array(list(range(n)), type=pa.int64()),
        'name': pa.array(['row_{}'.format(i % 4) for i in range(n)]),
        'vec': pa.array([schema.fields['vec'].codec.encode(
            schema.fields['vec'], v) for v in vecs], type=pa.binary()),
    })
    return schema, table, vecs


def test_decode_plan_matches_field_kinds():
    schema, table, vecs = _scalar_table()
    plan = decode_engine.compile_decode_plan(schema, ['id', 'name', 'vec'])
    columns = plan.execute(table)
    np.testing.assert_array_equal(columns['id'], np.arange(10))
    assert columns['name'].dtype == np.dtype(object)
    assert columns['name'][3] == 'row_3'
    np.testing.assert_array_equal(columns['vec'], np.stack(vecs))


def test_decode_plan_partition_and_decode_off():
    schema, table, _ = _scalar_table()
    plan = decode_engine.compile_decode_plan(
        schema, ['id', 'part'], partition_field_names={'part'}, decode=False)
    columns = plan.execute(table, partition_keys={'part': 'p_1'})
    assert list(columns['part']) == ['p_1'] * 10
    np.testing.assert_array_equal(columns['id'], np.arange(10))


def test_decode_plan_wraps_codec_failures():
    from petastorm_tpu.errors import DecodeFieldError
    schema = Unischema('Bad', [
        UnischemaField('vec', np.float32, (2, 2), NdarrayCodec(), False)])
    table = pa.table({'vec': pa.array([b'not-a-npy-blob'], type=pa.binary())})
    plan = decode_engine.compile_decode_plan(schema, ['vec'])
    with pytest.raises(DecodeFieldError) as exc_info:
        plan.execute(table, fragment_path='frag.parquet')
    assert exc_info.value.field_name == 'vec'
    assert exc_info.value.fragment_path == 'frag.parquet'


def test_stack_if_uniform_single_conversion_semantics():
    ragged = [np.zeros((2, 2)), np.zeros((3, 2))]
    field = UnischemaField('x', np.float64, (None, 2), None, False)
    assert isinstance(decode_engine.stack_if_uniform(ragged, field), list)
    uniform = decode_engine.stack_if_uniform(
        [np.ones((2, 2)), np.zeros((2, 2))], field)
    assert uniform.shape == (2, 2, 2)
    with_none = decode_engine.stack_if_uniform([np.ones((2, 2)), None], field)
    assert isinstance(with_none, list) and with_none[1] is None


def test_arrow_to_numpy_object_paths():
    strings = decode_engine.arrow_to_numpy(
        pa.chunked_array([pa.array(['a', None, 'b'])]))
    assert strings.dtype == np.dtype(object)
    assert strings[1] is None and strings[2] == 'b'
    lists = decode_engine.arrow_to_numpy(
        pa.chunked_array([pa.array([[1, 2], None, [3]])]))
    assert isinstance(lists, list) and lists[1] is None
    np.testing.assert_array_equal(lists[0], [1, 2])


# ------------------------------------------------------ predicate pushdown


def _pushdown_schema_and_table(n=64):
    schema = Unischema('PredSchema', [
        UnischemaField('id', np.int64, (), ScalarCodec(), False),
        UnischemaField('key', np.str_, (), ScalarCodec(), False),
        UnischemaField('score', np.float32, (), ScalarCodec(), False),
    ])
    rng = np.random.RandomState(7)
    table = pa.table({
        'id': pa.array([int(v) for v in rng.randint(0, 20, size=n)],
                       type=pa.int64()),
        'key': pa.array(['k_{}'.format(i % 9) for i in range(n)]),
        'score': pa.array([float(v) for v in rng.rand(n)], type=pa.float32()),
    })
    return schema, table


def _python_row_mask(predicate, schema, table):
    """The per-row reference: decode every predicate column, loop row dicts."""
    fields = sorted(predicate.get_fields())
    plan = decode_engine.compile_decode_plan(schema, fields)
    columns = plan.execute(table)
    mask = np.zeros(table.num_rows, dtype=bool)
    for i in range(table.num_rows):
        mask[i] = bool(predicate.do_include(
            {name: columns[name][i] for name in fields}))
    return mask


EQUIVALENCE_PREDICATES = [
    ('in_set_int', lambda: in_set({1, 5, 7, 19}, 'id')),
    ('in_set_empty', lambda: in_set(set(), 'id')),
    ('in_set_str', lambda: in_set({'k_2', 'k_8', 'missing'}, 'key')),
    ('in_set_float', lambda: in_set({0.25, 0.5}, 'score')),
    ('in_negate', lambda: in_negate(in_set({3, 4}, 'id'))),
    ('in_reduce_all', lambda: in_reduce(
        [in_set(set(range(10)), 'id'), in_set({'k_1', 'k_2', 'k_3'}, 'key')], all)),
    ('in_reduce_any', lambda: in_reduce(
        [in_set({1}, 'id'), in_negate(in_set({'k_0'}, 'key'))], any)),
    ('split_str', lambda: in_pseudorandom_split([0.3, 0.4, 0.3], 1, 'key')),
    ('split_int', lambda: in_pseudorandom_split([0.5, 0.5], 0, 'id')),
    ('nested', lambda: in_negate(in_reduce(
        [in_pseudorandom_split([0.6, 0.4], 0, 'key'), in_set({2, 4, 6}, 'id')],
        any))),
]


@pytest.mark.parametrize('name,make_predicate', EQUIVALENCE_PREDICATES)
def test_pushdown_mask_equals_python_row_mask(name, make_predicate):
    """Acceptance: bit-identical row selection for every compilable predicate."""
    schema, table = _pushdown_schema_and_table()
    predicate = make_predicate()
    compiled = decode_engine.compile_predicate(predicate, schema)
    assert compiled is not None, 'expected {} to compile'.format(name)
    mask = compiled.evaluate(table)
    np.testing.assert_array_equal(mask, _python_row_mask(predicate, schema, table))


def test_pushdown_str_bytes_families_never_cross_match():
    """Arrow would silently encode str<->bytes across string/binary columns;
    the compiled path must keep the Python answer (no match) instead."""
    schema = Unischema('Families', [
        UnischemaField('b', np.bytes_, (), ScalarCodec(), False),
        UnischemaField('s', np.str_, (), ScalarCodec(), False),
    ])
    table = pa.table({'b': pa.array([b'a', b'z'], type=pa.binary()),
                      's': pa.array(['a', 'z'])})
    for predicate in (in_set({'a'}, 'b'), in_set({b'a'}, 's')):
        compiled = decode_engine.compile_predicate(predicate, schema)
        assert compiled is not None
        mask = compiled.evaluate(table)
        np.testing.assert_array_equal(
            mask, _python_row_mask(predicate, schema, table))
        assert not mask.any()
    matching = decode_engine.compile_predicate(in_set({b'a'}, 'b'), schema)
    np.testing.assert_array_equal(matching.evaluate(table), [True, False])


def test_pushdown_out_of_range_int_set_falls_back_in_band():
    """pa.array raises OverflowError (not an Arrow error) for out-of-C-range
    ints; the leaf must fall back to the numpy mirror, not crash the worker."""
    schema = Unischema('Narrow', [
        UnischemaField('x', np.uint8, (), ScalarCodec(), False)])
    table = pa.table({'x': pa.array([0, 255, 7], type=pa.uint8())})
    predicate = in_set({-1, 255, 2 ** 70}, 'x')
    compiled = decode_engine.compile_predicate(predicate, schema)
    assert compiled is not None
    mask = compiled.evaluate(table)
    np.testing.assert_array_equal(mask, _python_row_mask(predicate, schema, table))
    np.testing.assert_array_equal(mask, [False, True, False])


def test_field_less_predicate_still_called_per_row():
    calls = []

    def always(*args):
        calls.append(1)
        return True

    mask = decode_engine.evaluate_predicate_mask(in_lambda([], always), {}, 4)
    np.testing.assert_array_equal(mask, [True] * 4)
    assert len(calls) == 4


def test_pushdown_split_is_deterministic_across_compiles():
    schema, table = _pushdown_schema_and_table()
    predicate = in_pseudorandom_split([0.5, 0.5], 1, 'key')
    first = decode_engine.compile_predicate(predicate, schema).evaluate(table)
    second = decode_engine.compile_predicate(predicate, schema).evaluate(table)
    np.testing.assert_array_equal(first, second)
    assert 0 < first.sum() < table.num_rows  # both buckets populated


def test_pushdown_handles_null_scalars_like_python():
    schema = Unischema('Nulls', [
        UnischemaField('id', np.int64, (), ScalarCodec(), True)])
    table = pa.table({'id': pa.array([1, None, 5, None], type=pa.int64())})
    predicate = in_set({1, 5}, 'id')
    compiled = decode_engine.compile_predicate(predicate, schema)
    mask = compiled.evaluate(table)
    np.testing.assert_array_equal(mask, [True, False, True, False])
    np.testing.assert_array_equal(mask, _python_row_mask(predicate, schema, table))


@pytest.mark.parametrize('name,predicate_factory', [
    ('in_lambda', lambda: in_lambda(['id'], lambda v: v > 3)),
    ('custom_reduce', lambda: in_reduce([in_set({1}, 'id')],
                                        lambda results: sum(results) > 0)),
    ('unknown_field', lambda: in_set({1}, 'no_such_field')),
])
def test_uncompilable_predicates_return_none(name, predicate_factory):
    schema, _ = _pushdown_schema_and_table()
    assert decode_engine.compile_predicate(predicate_factory(), schema) is None


def test_subclassed_predicate_is_not_compiled():
    """Exact-type gate: a subclass may override do_include semantics."""

    class _Flipped(in_set):
        def do_include(self, values):
            return not super().do_include(values)

    schema, _ = _pushdown_schema_and_table()
    assert decode_engine.compile_predicate(_Flipped({1}, 'id'), schema) is None


def test_partition_field_predicates_fall_back():
    schema, _ = _pushdown_schema_and_table()
    assert decode_engine.compile_predicate(
        in_set({'p_0'}, 'key'), schema, partition_field_names={'key'}) is None


def test_evaluate_predicate_mask_vectorized_and_row_paths_agree():
    columns = {'id': np.array([0, 1, 2, 3, 4, 5], dtype=np.int64)}
    vectorized = decode_engine.evaluate_predicate_mask(
        in_set({1, 4}, 'id'), columns, 6)
    np.testing.assert_array_equal(vectorized, [False, True, False, False, True,
                                               False])
    lam = in_lambda(['id'], lambda v: v % 2 == 0)
    row_looped = decode_engine.evaluate_predicate_mask(lam, columns, 6)
    np.testing.assert_array_equal(row_looped, [True, False, True, False, True,
                                               False])


# ------------------------------------------------- end-to-end reader paths


def test_reader_pushdown_matches_lambda_fallback(synthetic_dataset):
    """Same rows whether the predicate compiles (in_set) or not (in_lambda)."""
    with make_reader(synthetic_dataset.url, workers_count=1, num_epochs=1,
                     shuffle_row_groups=False,
                     predicate=in_set({0, 1, 2, 3}, 'id2')) as reader:
        pushdown_ids = sorted(row.id for row in reader)
    with make_reader(synthetic_dataset.url, workers_count=1, num_epochs=1,
                     shuffle_row_groups=False,
                     predicate=in_lambda(['id2'], lambda v: v in {0, 1, 2, 3})) \
            as reader:
        fallback_ids = sorted(row.id for row in reader)
    expected = sorted(row['id'] for row in synthetic_dataset.rows
                      if row['id2'] in {0, 1, 2, 3})
    assert pushdown_ids == expected
    assert fallback_ids == expected


def test_reader_pushdown_split_matches_row_reference(synthetic_dataset):
    """in_pseudorandom_split end to end: the worker's pushdown selection equals
    the predicate's own scalar answers."""
    predicate = in_pseudorandom_split([0.4, 0.6], 0, 'sensor_name')
    with make_reader(synthetic_dataset.url, workers_count=1, num_epochs=1,
                     shuffle_row_groups=False, predicate=predicate) as reader:
        got_ids = sorted(row.id for row in reader)
    expected = sorted(
        row['id'] for row in synthetic_dataset.rows
        if predicate.do_include({'sensor_name': row['sensor_name']}))
    assert got_ids == expected


def test_single_read_two_phase_reads_each_column_once(synthetic_dataset):
    """The predicate column (part of the read view) must not be re-read: rows
    and values still come out right, and the predicate table is reused."""
    with make_reader(synthetic_dataset.url, workers_count=1, num_epochs=1,
                     shuffle_row_groups=False,
                     schema_fields=['id', 'id2', 'matrix'],
                     predicate=in_set({1, 3}, 'id2')) as reader:
        rows = list(reader)
    expected = [row for row in synthetic_dataset.rows if row['id2'] in {1, 3}]
    assert sorted(r.id for r in rows) == sorted(row['id'] for row in expected)
    by_id = {row['id']: row for row in expected}
    for row in rows:
        np.testing.assert_array_equal(row.matrix, by_id[row.id]['matrix'])


def test_two_phase_predicate_outside_read_view(synthetic_dataset):
    """A predicate field the user did not select still drives the row selection
    (the reader widens the read view to cover it — established semantics), and
    the selected values come out right through the single-read assembly."""
    with make_reader(synthetic_dataset.url, workers_count=1, num_epochs=1,
                     shuffle_row_groups=False, schema_fields=['id', 'matrix'],
                     predicate=in_set({0}, 'id2')) as reader:
        rows = list(reader)
    expected_ids = sorted(row['id'] for row in synthetic_dataset.rows
                          if row['id2'] == 0)
    assert sorted(row.id for row in rows) == expected_ids
    by_id = {row['id']: row for row in synthetic_dataset.rows}
    for row in rows:
        np.testing.assert_array_equal(row.matrix, by_id[row.id]['matrix'])


# --------------------------------------------------- transform pre-pass


def test_transform_spec_without_func_skips_row_materialization(synthetic_dataset):
    spec = TransformSpec(removed_fields=['matrix_var', 'string_list'])
    with make_reader(synthetic_dataset.url, workers_count=1, num_epochs=1,
                     shuffle_row_groups=False,
                     schema_fields=['id', 'matrix', 'matrix_var', 'string_list'],
                     transform_spec=spec) as reader:
        rows = list(reader)
    assert len(rows) == len(synthetic_dataset.rows)
    assert not hasattr(rows[0], 'matrix_var')
    np.testing.assert_array_equal(
        sorted(row.id for row in rows),
        sorted(row['id'] for row in synthetic_dataset.rows))


def test_batched_transform_spec_matches_row_transform(synthetic_dataset):
    """A declared-batched columns-dict func must produce exactly what the
    per-row func path produces."""
    def row_func(row):
        row['matrix'] = row['matrix'] * 2.0
        return row

    def batched_func(columns):
        columns['matrix'] = columns['matrix'] * 2.0
        return columns

    kwargs = dict(workers_count=1, num_epochs=1, shuffle_row_groups=False,
                  schema_fields=['id', 'matrix'])
    with make_reader(synthetic_dataset.url,
                     transform_spec=TransformSpec(row_func), **kwargs) as reader:
        row_result = {row.id: row.matrix for row in reader}
    with make_reader(synthetic_dataset.url,
                     transform_spec=TransformSpec(batched_func, batched=True),
                     **kwargs) as reader:
        batched_result = {row.id: row.matrix for row in reader}
    assert set(row_result) == set(batched_result)
    for key, value in row_result.items():
        np.testing.assert_array_equal(value, batched_result[key])
