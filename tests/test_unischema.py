"""Unischema unit tests (model: petastorm/tests/test_unischema.py, 501 LoC)."""

from decimal import Decimal

import numpy as np
import pyarrow as pa
import pytest

from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
from petastorm_tpu.unischema import (Unischema, UnischemaField, decode_row,
                                     dict_to_encoded_row, insert_explicit_nulls,
                                     match_unischema_fields)


def _schema():
    return Unischema('Test', [
        UnischemaField('id', np.int64, (), ScalarCodec(), False),
        UnischemaField('name', np.str_, (), ScalarCodec(), False),
        UnischemaField('matrix', np.float32, (3, 2), NdarrayCodec(), False),
        UnischemaField('opt', np.int32, (), ScalarCodec(), True),
    ])


class TestField:
    def test_equality_value_based(self):
        f1 = UnischemaField('a', np.int32, (), ScalarCodec(), False)
        f2 = UnischemaField('a', np.int32, (), ScalarCodec(), False)
        assert f1 == f2 and hash(f1) == hash(f2)

    def test_inequality(self):
        f1 = UnischemaField('a', np.int32, (), ScalarCodec(), False)
        assert f1 != UnischemaField('b', np.int32, (), ScalarCodec(), False)
        assert f1 != UnischemaField('a', np.int64, (), ScalarCodec(), False)
        assert f1 != UnischemaField('a', np.int32, (2,), NdarrayCodec(), False)
        assert f1 != UnischemaField('a', np.int32, (), ScalarCodec(), True)

    def test_json_roundtrip(self):
        f = UnischemaField('m', np.float32, (None, 4), NdarrayCodec(), True)
        restored = UnischemaField.from_json_dict(f.to_json_dict())
        assert restored == f

    def test_json_roundtrip_decimal(self):
        f = UnischemaField('d', Decimal, (), ScalarCodec(), False)
        assert UnischemaField.from_json_dict(f.to_json_dict()) == f

    def test_shape_dtype_struct(self):
        f = UnischemaField('m', np.float32, (3, 2), NdarrayCodec(), False)
        sds = f.shape_dtype_struct(batch_dims=(8,))
        assert sds.shape == (8, 3, 2)
        assert sds.dtype == np.float32

    def test_shape_dtype_struct_rejects_ragged(self):
        f = UnischemaField('m', np.float32, (None,), NdarrayCodec(), False)
        with pytest.raises(ValueError):
            f.shape_dtype_struct()


class TestSchema:
    def test_field_order_preserved(self):
        schema = _schema()
        assert list(schema.fields) == ['id', 'name', 'matrix', 'opt']

    def test_attribute_access(self):
        schema = _schema()
        assert schema.id.name == 'id'
        assert schema.matrix.shape == (3, 2)

    def test_duplicate_field_raises(self):
        with pytest.raises(ValueError):
            Unischema('S', [UnischemaField('a', np.int32, (), ScalarCodec(), False),
                            UnischemaField('a', np.int64, (), ScalarCodec(), False)])

    def test_view_by_name(self):
        view = _schema().create_schema_view(['id', 'name'])
        assert list(view.fields) == ['id', 'name']

    def test_view_by_regex(self):
        view = _schema().create_schema_view(['.*a.*'])
        assert list(view.fields) == ['name', 'matrix']

    def test_view_by_field_instance(self):
        schema = _schema()
        view = schema.create_schema_view([schema.id])
        assert list(view.fields) == ['id']

    def test_view_no_match_raises(self):
        with pytest.raises(ValueError):
            _schema().create_schema_view(['nomatch'])

    def test_view_field_not_member_raises(self):
        other = UnischemaField('zzz', np.int32, (), ScalarCodec(), False)
        with pytest.raises(ValueError):
            _schema().create_schema_view([other])

    def test_namedtuple_cached_identity(self):
        s1, s2 = _schema(), _schema()
        assert s1.namedtuple is s2.namedtuple

    def test_make_namedtuple(self):
        schema = _schema()
        row = schema.make_namedtuple(id=1, name='a', matrix=None, opt=None)
        assert row.id == 1 and row.name == 'a'

    def test_json_roundtrip(self):
        schema = _schema()
        restored = Unischema.from_json_dict(schema.to_json_dict())
        assert restored == schema

    def test_arrow_schema_render(self):
        arrow = _schema().as_arrow_schema()
        assert arrow.field('id').type == pa.int64()
        assert arrow.field('matrix').type == pa.binary()
        assert arrow.field('opt').nullable


class TestMatchFields:
    def test_fullmatch_semantics(self):
        schema = _schema()
        # 'id' must not prefix-match 'idx'-like names; 'na' must not match 'name'
        assert [f.name for f in match_unischema_fields(schema, ['na'])] == []
        assert [f.name for f in match_unischema_fields(schema, ['name'])] == ['name']
        assert {f.name for f in match_unischema_fields(schema, ['id', 'opt'])} == {'id', 'opt'}

    def test_empty(self):
        assert match_unischema_fields(_schema(), []) == []


class TestEncodeDecode:
    def test_roundtrip(self):
        schema = _schema()
        matrix = np.random.rand(3, 2).astype(np.float32)
        row = {'id': 7, 'name': 'seven', 'matrix': matrix, 'opt': 3}
        encoded = dict_to_encoded_row(schema, row)
        assert isinstance(encoded['matrix'], bytes)
        decoded = decode_row(encoded, schema)
        assert decoded['id'] == 7
        np.testing.assert_array_equal(decoded['matrix'], matrix)
        assert decoded['opt'] == 3

    def test_nullable_missing_becomes_none(self):
        schema = _schema()
        encoded = dict_to_encoded_row(schema, {'id': 1, 'name': 'x',
                                               'matrix': np.zeros((3, 2), np.float32)})
        assert encoded['opt'] is None

    def test_missing_required_raises(self):
        with pytest.raises(ValueError):
            dict_to_encoded_row(_schema(), {'id': 1})

    def test_unknown_field_raises(self):
        with pytest.raises(ValueError, match='not part of schema'):
            dict_to_encoded_row(_schema(), {'id': 1, 'bogus': 2, 'name': 'x',
                                            'matrix': np.zeros((3, 2), np.float32)})

    def test_insert_explicit_nulls(self):
        schema = _schema()
        row = {'id': 1, 'name': 'x', 'matrix': 'm'}
        out = insert_explicit_nulls(schema, dict(row))
        assert out['opt'] is None


class TestArrowInference:
    def test_infer_scalars_and_lists(self):
        arrow_schema = pa.schema([
            pa.field('i', pa.int32()),
            pa.field('f', pa.float64()),
            pa.field('s', pa.string()),
            pa.field('v', pa.list_(pa.float32())),
            pa.field('d', pa.decimal128(10, 2)),
        ])
        schema = Unischema.from_arrow_schema(arrow_schema)
        assert np.dtype(schema.i.numpy_dtype) == np.int32
        assert schema.v.shape == (None,)
        assert schema.d.numpy_dtype is Decimal
        assert schema.s.numpy_dtype == np.dtype('str_')

    def test_unsupported_skipped_with_warning(self):
        arrow_schema = pa.schema([
            pa.field('ok', pa.int32()),
            pa.field('bad', pa.list_(pa.list_(pa.int32()))),
        ])
        with pytest.warns(UserWarning):
            schema = Unischema.from_arrow_schema(arrow_schema)
        assert list(schema.fields) == ['ok']

    def test_unsupported_raises_when_strict(self):
        arrow_schema = pa.schema([pa.field('bad', pa.list_(pa.list_(pa.int32())))])
        with pytest.raises(ValueError):
            Unischema.from_arrow_schema(arrow_schema, omit_unsupported_fields=False)


class TestReferenceEdgeParity:
    """Edge behaviors the reference pins (test_unischema.py:field-name conflicts,
    mixed-view duplicates)."""

    def test_field_name_conflicting_with_attribute(self):
        # A field named like a Unischema attribute/method must not shadow it:
        # the real API wins, the field stays reachable via .fields['name'].
        schema = Unischema('S', [
            UnischemaField('fields', np.int64, (), ScalarCodec(), False),
            UnischemaField('create_schema_view', np.int64, (), ScalarCodec(), False),
        ])
        assert isinstance(schema.fields, dict)
        assert callable(schema.create_schema_view)
        assert schema.fields['fields'].name == 'fields'
        view = schema.create_schema_view(['fields'])
        assert list(view.fields) == ['fields']

    def test_view_mixed_regex_and_field_instances_dedup(self):
        # Regexes and UnischemaField instances mix in one view; overlapping
        # selections dedup (reference: create_schema_view_using_regex_and_
        # unischema_fields_with_duplicates).
        f_id = UnischemaField('id', np.int64, (), ScalarCodec(), False)
        schema = Unischema('S2', [
            f_id, UnischemaField('id2', np.int64, (), ScalarCodec(), False),
            UnischemaField('other', np.int64, (), ScalarCodec(), False),
        ])
        view = schema.create_schema_view(['id.*', f_id])
        assert sorted(view.fields) == ['id', 'id2']
