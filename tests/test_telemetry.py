"""Pipeline telemetry tests (ISSUE 3): registry primitives, cross-process span
merging, export surfaces, bottleneck attribution, and the overhead budget.

Covers the acceptance criteria:

- histogram bucket boundaries (including 0 and values past the last bucket);
- cross-process sidecar merge through a spawned process pool with the shm
  transport — non-zero per-stage histograms for stages executed in worker
  processes — including under a mid-epoch worker kill + respawn (faultinject);
- snapshot-while-writing consistency (concurrent observers never tear the
  ``sum(buckets) >= count`` invariant);
- the overhead guard: instrumented iteration stays within budget of
  uninstrumented, and the per-observe hot path stays micro-cheap;
- ``LoaderStats`` thread-safety (the satellite race fix) and the
  ``wire_bytes_copied_per_batch`` running mean sourced from the histogram.
"""

import glob
import json
import os
import threading
import time

import numpy as np
import pytest

from petastorm_tpu.telemetry import (MetricsRegistry, StageRecorder,
                                     merge_snapshots, set_telemetry_enabled,
                                     stage_span, telemetry_enabled)
from petastorm_tpu.telemetry.analyze import attribute_bottleneck, format_report
from petastorm_tpu.telemetry.export import (JsonlEventLogger, load_snapshot,
                                            to_prometheus_text)
from petastorm_tpu.telemetry.registry import (BYTES_UNIT, DEFAULT_NUM_BUCKETS,
                                              SECONDS_UNIT, bucket_index,
                                              bucket_upper_bound)


# ---------------------------------------------------------------------------
# histogram / registry units
# ---------------------------------------------------------------------------

class TestHistogramBuckets(object):
    def test_bucket_boundaries_power_of_two(self):
        unit = SECONDS_UNIT
        # 0 and negatives land in bucket 0; the boundary value v == unit*2^i is
        # INCLUSIVE in bucket i; the first value past it starts bucket i+1
        assert bucket_index(0.0, unit) == 0
        assert bucket_index(-1.0, unit) == 0
        assert bucket_index(unit, unit) == 0
        assert bucket_index(unit * 1.001, unit) == 1
        assert bucket_index(unit * 2, unit) == 1
        assert bucket_index(unit * 2.001, unit) == 2
        assert bucket_index(unit * 4, unit) == 2
        for i in range(1, DEFAULT_NUM_BUCKETS - 1):
            v = unit * (1 << i)
            assert bucket_index(v, unit) == i, i
            assert v <= bucket_upper_bound(i, unit)

    def test_values_past_last_bucket_clamp(self):
        # > max bucket: clamped into the last (+Inf) bucket, never lost
        huge = SECONDS_UNIT * (1 << (DEFAULT_NUM_BUCKETS + 8))
        assert bucket_index(huge, SECONDS_UNIT) == DEFAULT_NUM_BUCKETS - 1
        assert bucket_upper_bound(DEFAULT_NUM_BUCKETS - 1,
                                  SECONDS_UNIT) == float('inf')
        registry = MetricsRegistry()
        registry.observe('stage', huge)
        registry.observe('stage', 0.0)
        snap = registry.snapshot()['histograms']['stage']
        assert snap['count'] == 2
        assert snap['buckets'][str(DEFAULT_NUM_BUCKETS - 1)] == 1
        assert snap['buckets']['0'] == 1
        assert snap['max'] == huge

    def test_snapshot_is_json_safe_and_mean_correct(self):
        registry = MetricsRegistry()
        for v in (0.001, 0.003):
            registry.observe('decode', v)
        registry.inc('batches', 2)
        registry.gauge('depth').set(3)
        snap = json.loads(json.dumps(registry.snapshot()))
        hist = snap['histograms']['decode']
        assert hist['count'] == 2
        assert hist['mean'] == pytest.approx(0.002)
        assert snap['counters']['batches'] == 2
        assert snap['gauges']['depth'] == 3.0

    def test_merge_snapshots_additive(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        a.observe('s', 0.25)
        a.inc('n', 1)
        b.observe('s', 0.75)
        b.inc('n', 2)
        merged = merge_snapshots(a.snapshot(), b.snapshot(), None)
        assert merged['histograms']['s']['count'] == 2
        assert merged['histograms']['s']['sum'] == pytest.approx(1.0)
        assert merged['counters']['n'] == 3


def test_snapshot_while_writing_consistency():
    """Concurrent observers vs a snapshotting reader: every snapshot satisfies
    ``sum(buckets) >= count`` (no phantom observations) and counts are monotone;
    after joining, the totals are exact."""
    registry = MetricsRegistry()
    per_thread = 4000
    n_threads = 4
    stop = threading.Event()

    def writer(seed):
        rng = np.random.RandomState(seed)
        values = rng.rand(per_thread) * 1e-3
        for v in values:
            registry.observe('stage', float(v))

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    last_count = 0
    while any(t.is_alive() for t in threads):
        snap = registry.snapshot()['histograms'].get('stage')
        if snap is None:
            continue
        assert sum(snap['buckets'].values()) >= snap['count']
        assert snap['count'] >= last_count
        last_count = snap['count']
    for t in threads:
        t.join()
    stop.set()
    final = registry.snapshot()['histograms']['stage']
    assert final['count'] == per_thread * n_threads
    assert sum(final['buckets'].values()) == final['count']


def test_stage_recorder_drain_is_per_thread():
    recorder = StageRecorder()
    recorder.record('decode', 0.01)
    seen = {}

    def other():
        recorder.record('decode', 0.02)
        seen['other'] = recorder.drain()

    t = threading.Thread(target=other)
    t.start()
    t.join()
    mine = recorder.drain()
    assert mine['decode']['count'] == 1
    assert mine['decode']['sum'] == pytest.approx(0.01)
    assert seen['other']['decode']['count'] == 1
    assert recorder.drain() is None  # drained clean


def test_stage_span_records_and_disable_switch():
    recorder_probe = MetricsRegistry()
    with stage_span('fs_open'):
        time.sleep(0.002)
    from petastorm_tpu.telemetry import drain_stage_times
    drained = drain_stage_times()
    assert drained['fs_open']['count'] == 1
    assert drained['fs_open']['sum'] >= 0.002
    assert telemetry_enabled()
    set_telemetry_enabled(False)
    try:
        with stage_span('fs_open'):
            pass
        recorder_probe.observe('x', 1.0)
        recorder_probe.inc('c')
        assert drain_stage_times() is None
        assert recorder_probe.snapshot() == {'histograms': {}, 'counters': {},
                                             'gauges': {}}
    finally:
        set_telemetry_enabled(True)


def test_observe_overhead_budget():
    """The hot path must stay micro-cheap: a single observe() (and a stage_span
    pair) well under 50 µs on any plausible host — the budget that keeps
    per-rowgroup instrumentation invisible next to Parquet IO."""
    registry = MetricsRegistry()
    hist = registry.histogram('stage')
    n = 20000
    start = time.perf_counter()
    for i in range(n):
        hist.observe(1e-4)
    per_observe = (time.perf_counter() - start) / n
    start = time.perf_counter()
    for i in range(n):
        with stage_span('stage'):
            pass
    per_span = (time.perf_counter() - start) / n
    from petastorm_tpu.telemetry import drain_stage_times
    drain_stage_times()  # don't leak this thread's cells into later tests
    assert per_observe < 50e-6, 'observe() costs {:.1f}us'.format(per_observe * 1e6)
    assert per_span < 100e-6, 'stage_span costs {:.1f}us'.format(per_span * 1e6)


# ---------------------------------------------------------------------------
# export surfaces
# ---------------------------------------------------------------------------

def test_prometheus_exposition_format():
    registry = MetricsRegistry()
    registry.observe('decode', 3e-6)   # bucket 2 (2..4 us)
    registry.observe('decode', 0.5)
    registry.inc('batches', 7)
    registry.gauge('inflight').set(2)
    text = to_prometheus_text(registry.snapshot())
    assert '# TYPE petastorm_tpu_decode histogram' in text
    assert 'petastorm_tpu_decode_count 2' in text
    assert 'petastorm_tpu_decode_bucket{le="+Inf"} 2' in text
    # cumulative: the 4us bucket already includes the 3us observation
    assert 'petastorm_tpu_decode_bucket{le="4e-06"} 1' in text
    assert 'petastorm_tpu_batches 7' in text
    assert '# TYPE petastorm_tpu_inflight gauge' in text


def test_jsonl_logger_and_load_snapshot(tmp_path):
    registry = MetricsRegistry()
    registry.observe('decode', 0.1)
    path = str(tmp_path / 'events.jsonl')
    logger = JsonlEventLogger(path, interval_s=60)
    assert logger.emit(registry.snapshot(), event='one')
    registry.observe('decode', 0.2)
    assert logger.emit(registry.snapshot(), event='two')
    # throttle: immediately after an emit, maybe_emit is not due
    assert not logger.due()
    assert not logger.maybe_emit(registry.snapshot())
    lines = open(path).read().strip().splitlines()
    assert len(lines) == 2
    # load_snapshot takes the LAST (cumulative) record
    snap = load_snapshot(path)
    assert snap['histograms']['decode']['count'] == 2


def test_prometheus_help_lines_and_escaping():
    """Satellite (ISSUE 6): every metric carries a # HELP/# TYPE pair, and a
    pathological metric name — quotes, backslash, newline — degrades to a
    sanitized series with escaped HELP text, never to an exposition a scraper
    rejects (no raw newline mid-line, no unescaped quote in a label)."""
    from petastorm_tpu.telemetry.export import escape_label_value
    registry = MetricsRegistry()
    evil = 'weird "stage"\nwith\\backslash'
    registry.observe(evil, 0.5)
    registry.inc('batches', 1)
    text = to_prometheus_text(registry.snapshot())
    for line in text.strip().splitlines():
        # a pathological name must never smuggle a raw partial line through
        assert line.startswith(('#', 'petastorm_tpu_')), line
    assert '# HELP petastorm_tpu_batches ' in text
    assert '# TYPE petastorm_tpu_batches counter' in text
    # the HELP line for the evil metric carries the ESCAPED original name
    help_lines = [ln for ln in text.splitlines()
                  if ln.startswith('# HELP petastorm_tpu_weird')]
    assert len(help_lines) == 1
    assert '\\n' in help_lines[0] and '\\\\' in help_lines[0]
    # label-value escaping contract (backslash, quote, newline)
    assert escape_label_value('a"b\\c\nd') == 'a\\"b\\\\c\\nd'


def test_jsonl_logger_max_bytes_rotation(tmp_path):
    """Satellite (ISSUE 6): with max_bytes set, the log rotates to <path>.1
    instead of growing without bound; default (None) keeps the old unbounded
    behavior. Lines are never split across the rotation boundary."""
    registry = MetricsRegistry()
    registry.observe('decode', 0.1)
    snapshot = registry.snapshot()
    line_bytes = len(json.dumps({'ts': 0.0, 'event': 'e', 'pid': 0,
                                 'telemetry': snapshot})) + 1
    path = str(tmp_path / 'events.jsonl')
    logger = JsonlEventLogger(path, interval_s=0, max_bytes=int(line_bytes * 2.5))
    for _ in range(5):
        assert logger.emit(snapshot, event='e')
    rotated = path + '.1'
    assert os.path.exists(rotated)
    # every surviving line is intact JSON, and the cap bounds both files
    for p in (path, rotated):
        lines = open(p).read().strip().splitlines()
        assert lines, p
        for ln in lines:
            assert json.loads(ln)['telemetry']['histograms']['decode']
        assert os.path.getsize(p) <= line_bytes * 3
    # unbounded default: no rotation however much is written
    path2 = str(tmp_path / 'unbounded.jsonl')
    logger2 = JsonlEventLogger(path2, interval_s=0)
    for _ in range(5):
        assert logger2.emit(snapshot, event='e')
    assert not os.path.exists(path2 + '.1')
    assert len(open(path2).read().strip().splitlines()) == 5


def test_jsonl_logger_rotation_chain(tmp_path, monkeypatch):
    """Satellite (ISSUE 13): ``max_rotations`` keeps a ``.1 -> .N`` chain of
    rotated generations (oldest dropped off the end), so a long-running
    manifest log retains history instead of keeping exactly one ``.1``;
    ``PETASTORM_TPU_TELEMETRY_JSONL_ROTATIONS`` configures it from the env."""
    from petastorm_tpu.telemetry.export import env_rotation_settings
    line_bytes = len(json.dumps({'ts': 0.0, 'event': 'e', 'pid': 0,
                                 'telemetry': {}, 'n': 0})) + 40
    path = str(tmp_path / 'chain.jsonl')
    logger = JsonlEventLogger(path, interval_s=0, max_bytes=line_bytes,
                              max_rotations=3)
    for n in range(6):  # every line rotates: 6 writes -> live + .1/.2/.3
        assert logger.emit({}, event='e', n=n)
    assert os.path.exists(path + '.1')
    assert os.path.exists(path + '.2')
    assert os.path.exists(path + '.3')
    assert not os.path.exists(path + '.4')  # the chain is bounded
    # generation order: live file holds the newest line, .3 the oldest kept
    def seq(p):
        return [json.loads(ln)['n'] for ln in open(p).read().splitlines()]
    assert seq(path) == [5]
    assert seq(path + '.1') == [4]
    assert seq(path + '.2') == [3]
    assert seq(path + '.3') == [2]  # n=0,1 fell off the end
    # default stays the prior single-.1 behavior
    path2 = str(tmp_path / 'single.jsonl')
    logger2 = JsonlEventLogger(path2, interval_s=0, max_bytes=line_bytes)
    for n in range(4):
        assert logger2.emit({}, event='e', n=n)
    assert os.path.exists(path2 + '.1')
    assert not os.path.exists(path2 + '.2')
    # env plumbing
    monkeypatch.setenv('PETASTORM_TPU_TELEMETRY_JSONL_MAX_BYTES', '123')
    monkeypatch.setenv('PETASTORM_TPU_TELEMETRY_JSONL_ROTATIONS', '7')
    assert env_rotation_settings() == (123, 7)
    monkeypatch.setenv('PETASTORM_TPU_TELEMETRY_JSONL_ROTATIONS', 'junk')
    assert env_rotation_settings()[1] == 1


def test_prometheus_no_duplicate_inf_bucket():
    """An observation clamped into the LAST bucket must not yield two
    le=\"+Inf\" series (scrapers reject duplicate series)."""
    registry = MetricsRegistry()
    registry.observe('stage', SECONDS_UNIT * (1 << (DEFAULT_NUM_BUCKETS + 4)))
    text = to_prometheus_text(registry.snapshot())
    assert text.count('petastorm_tpu_stage_bucket{le="+Inf"}') == 1
    assert 'petastorm_tpu_stage_bucket{le="+Inf"} 1' in text


def test_load_snapshot_reads_doctor_report(tmp_path):
    """The analyze CLI must accept a doctor --json report, whose snapshot nests
    under report['telemetry']['snapshot']."""
    registry = MetricsRegistry()
    registry.observe('decode', 0.2)
    report = {'healthy': True,
              'telemetry': {'snapshot': registry.snapshot(),
                            'bottleneck': {'top_stage': 'decode'}}}
    path = tmp_path / 'doctor.json'
    path.write_text(json.dumps(report))
    snap = load_snapshot(str(path))
    assert snap['histograms']['decode']['count'] == 1


def test_load_snapshot_rejects_non_snapshot(tmp_path):
    path = tmp_path / 'junk.json'
    path.write_text('{"hello": 1}')
    with pytest.raises(ValueError, match='histograms'):
        load_snapshot(str(path))


# ---------------------------------------------------------------------------
# attribution
# ---------------------------------------------------------------------------

def test_attribution_ranks_and_maps_knob():
    registry = MetricsRegistry()
    for _ in range(4):
        registry.observe('decode', 0.2)
    registry.observe('rowgroup_read', 0.1)
    registry.observe('cache_miss', 0.9)  # envelope: excluded from shares
    registry.observe('wire_bytes_copied', 4096, unit=BYTES_UNIT)  # not a time
    report = attribute_bottleneck(registry.snapshot())
    assert report['top_stage'] == 'decode'
    assert report['ranked'][0]['share'] == pytest.approx(0.8 / 0.9, abs=1e-3)
    assert 'workers_count' in report['recommendation']
    assert report['envelopes'] == {'cache_miss': 0.9}
    assert all(e['stage'] != 'wire_bytes_copied' for e in report['ranked'])
    text = format_report(report)
    assert 'decode' in text and 'bottleneck' in text


def test_attribution_empty_snapshot():
    report = attribute_bottleneck({'histograms': {}})
    assert report['top_stage'] is None
    assert report['ranked'] == []
    assert 'no stage timings' in report['recommendation']
    assert 'no stage timings' in format_report(report)


# ---------------------------------------------------------------------------
# end-to-end: cross-process sidecar merge
# ---------------------------------------------------------------------------

def _write_store(root, num_rows=64, n_files=4, vec_len=8):
    from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_rows
    from petastorm_tpu.unischema import Unischema, UnischemaField
    schema = Unischema('TelemetryProbe', [
        UnischemaField('id', np.int64, (), ScalarCodec(), False),
        UnischemaField('vec', np.float32, (vec_len,), NdarrayCodec(), False),
    ])
    url = 'file://' + str(root)
    write_rows(url, schema,
               [{'id': i, 'vec': np.full(vec_len, i, np.float32)}
                for i in range(num_rows)],
               n_files=n_files, rowgroup_size_mb=1)
    return url


#: worker-process stages that MUST show up in the merged snapshot of a
#: process-pool read — the proof the sidecar merge crosses the process boundary
_WORKER_STAGES = ('rowgroup_read', 'decode')


def test_cross_process_sidecar_merge_shm(tmp_path):
    """Acceptance (ISSUE 3): a snapshot from a ``make_reader(...,
    workers_count>1, shm_transport=True)`` run shows non-zero per-stage
    histograms for stages executed in worker PROCESSES, plus the pool-side shm
    stages, and the attribution report runs off it."""
    from petastorm_tpu import make_reader

    url = _write_store(tmp_path / 'store', num_rows=64, n_files=8)
    with make_reader(url, reader_pool_type='process', workers_count=2,
                     num_epochs=1, shuffle_row_groups=False,
                     shm_transport=True) as reader:
        ids = sorted(int(row.id) for row in reader)
        snap = reader.telemetry_snapshot()
        diag = reader.diagnostics
    assert ids == list(range(64))
    assert diag['shm_batches'] > 0
    hists = snap['histograms']
    for stage in _WORKER_STAGES:
        assert hists[stage]['count'] > 0, stage
        assert hists[stage]['sum'] > 0, stage
        assert sum(hists[stage]['buckets'].values()) == hists[stage]['count']
    # consumer-side shm stages recorded by the pool registry
    assert hists['shm_map']['count'] > 0
    assert hists['wire_bytes_copied']['count'] > 0
    # diagnostics carries the same snapshot for dashboards
    assert diag['telemetry']['histograms']['decode']['count'] > 0
    report = attribute_bottleneck(snap)
    assert report['top_stage'] is not None
    json.dumps(snap)  # the whole snapshot is JSON-exportable


@pytest.mark.faultinject
def test_sidecar_merge_survives_worker_respawn(tmp_path):
    """A worker SIGKILL-ed mid-epoch: the replacement's sidecars keep merging and
    the final snapshot still covers at least every delivered batch's stages (the
    killed worker's unpublished in-flight item is the only loss)."""
    from petastorm_tpu import make_reader
    from petastorm_tpu.test_util.fault_injection import (
        FaultRule, FaultSchedule, fault_injecting_filesystem)

    url = _write_store(tmp_path / 'store', num_rows=64, n_files=8)
    target = os.path.basename(sorted(glob.glob(
        os.path.join(str(tmp_path / 'store'), '**', '*.parquet'),
        recursive=True))[3])
    sched = FaultSchedule(tmp_path / 'faults',
                          [FaultRule(target, kind='kill', times=1)])
    with make_reader(url, reader_pool_type='process', workers_count=2,
                     num_epochs=1, shuffle_row_groups=False, shm_transport=True,
                     filesystem=fault_injecting_filesystem(sched)) as reader:
        ids = sorted(int(row.id) for row in reader)
        snap = reader.telemetry_snapshot()
        diag = reader.diagnostics
    assert ids == list(range(64))
    assert diag['workers_respawned'] == 1
    hists = snap['histograms']
    # 8 fragments -> 8 rowgroup_read spans minimum would hold fault-free; with
    # one kill, the re-read piece is read again by the respawned worker, so the
    # count is >= the published-batch count and definitely non-zero
    assert hists['rowgroup_read']['count'] >= 8 - 1
    assert hists['decode']['count'] > 0


def test_telemetry_disabled_reader_stays_clean(tmp_path):
    """PETASTORM_TPU_TELEMETRY=0: the pipeline still works and the snapshot's
    latency histograms stay empty (the overhead escape hatch really disengages
    the instrumentation)."""
    from petastorm_tpu import make_reader

    url = _write_store(tmp_path / 'store', num_rows=16, n_files=2)
    from petastorm_tpu.telemetry import drain_stage_times
    drain_stage_times()  # shed any cells left behind by earlier tests
    set_telemetry_enabled(False)
    try:
        with make_reader(url, reader_pool_type='dummy', num_epochs=1,
                         shuffle_row_groups=False) as reader:
            n = sum(1 for _ in reader)
            snap = reader.telemetry_snapshot()
    finally:
        set_telemetry_enabled(True)
    assert n == 16
    assert not snap['histograms']


def test_instrumented_iteration_overhead_within_budget(tmp_path):
    """Overhead guard (acceptance): an instrumented epoch stays within budget of
    an uninstrumented one over the same store. Generous bound (2x + 0.25s
    absolute floor) — per-stage spans are nanoseconds against millisecond
    rowgroup IO, so a real regression would blow far past it while shared-host
    timer noise stays inside it."""
    from petastorm_tpu import make_reader

    url = _write_store(tmp_path / 'store', num_rows=256, n_files=4, vec_len=32)

    def epoch_seconds():
        with make_reader(url, reader_pool_type='dummy', num_epochs=1,
                         shuffle_row_groups=False) as reader:
            start = time.perf_counter()
            n = sum(batch.num_rows for batch in reader.iter_columnar())
            elapsed = time.perf_counter() - start
        assert n == 256
        return elapsed

    epoch_seconds()  # warm the page cache / imports for both measurements
    set_telemetry_enabled(False)
    try:
        uninstrumented = min(epoch_seconds() for _ in range(3))
    finally:
        set_telemetry_enabled(True)
    instrumented = min(epoch_seconds() for _ in range(3))
    assert instrumented <= uninstrumented * 2.0 + 0.25, \
        'instrumented {:.4f}s vs uninstrumented {:.4f}s'.format(
            instrumented, uninstrumented)


# ---------------------------------------------------------------------------
# LoaderStats satellites
# ---------------------------------------------------------------------------

def test_loader_stats_concurrent_mutation_race():
    """Satellite: LoaderStats must actually be thread-safe — concurrent add()
    from N threads (the consumer/producer split the loader really has) loses no
    updates, and as_dict() snapshots never explode mid-write."""
    from petastorm_tpu.parallel.loader import LoaderStats

    stats = LoaderStats()
    n_threads, iters = 4, 5000
    snapshots = []

    def hammer():
        for _ in range(iters):
            stats.add(batches=1, rows=2, wait_time_s=0.001, total_time_s=0.002)

    def snapshotter():
        for _ in range(200):
            d = stats.as_dict()
            assert d['batches'] >= 0
            snapshots.append(d['batches'])

    threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
    threads.append(threading.Thread(target=snapshotter))
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert stats.batches == n_threads * iters
    assert stats.rows == 2 * n_threads * iters
    assert stats.wait_time_s == pytest.approx(0.001 * n_threads * iters)
    assert snapshots == sorted(snapshots)  # monotone under concurrent adds
    with pytest.raises(AttributeError):
        stats.add(nonsense=1)


def test_wire_bytes_copied_running_mean_from_histogram():
    """Satellite: wire_bytes_copied_per_batch mirrors the HISTOGRAM mean
    (stream-wide), not the pool's last-writer scalar."""
    from petastorm_tpu.parallel.loader import JaxDataLoader
    from petastorm_tpu.telemetry import MetricsRegistry

    registry = MetricsRegistry()
    for v in (1000, 3000):
        registry.observe('wire_bytes_copied', v, unit=BYTES_UNIT)

    class FakeReader(object):
        num_epochs = 1
        io_retries = 3
        quarantine = ()

        @property
        def diagnostics(self):
            return {'cache_hits': 5, 'cache_misses': 1, 'shm_batches': 2,
                    'shm_fallback_batches': 0,
                    # the stale last-writer scalar the histogram must win over
                    'wire_bytes_copied_per_batch': 99999.0,
                    'telemetry': registry.snapshot()}

    loader = JaxDataLoader(FakeReader(), batch_size=1, device_put=False)
    loader._sync_resilience_stats()
    assert loader.stats.wire_bytes_copied_per_batch == pytest.approx(2000.0)
    assert loader.stats.cache_hits == 5
    assert loader.stats.io_retries == 3

    class NoHistReader(FakeReader):
        @property
        def diagnostics(self):
            return {'wire_bytes_copied_per_batch': 123.4,
                    'telemetry': {'histograms': {}}}

    loader = JaxDataLoader(NoHistReader(), batch_size=1, device_put=False)
    loader._sync_resilience_stats()
    assert loader.stats.wire_bytes_copied_per_batch == pytest.approx(123.4)


def test_loader_telemetry_snapshot_merges_reader(tmp_path):
    """JaxDataLoader.telemetry_snapshot covers loader stages AND the reader's
    cross-process view in one dict."""
    from petastorm_tpu import make_reader
    from petastorm_tpu.parallel import JaxDataLoader

    url = _write_store(tmp_path / 'store', num_rows=32, n_files=2)
    reader = make_reader(url, reader_pool_type='dummy', num_epochs=1,
                         shuffle_row_groups=False)
    loader = JaxDataLoader(reader, batch_size=8, device_put=False,
                           drop_last=False)
    rows = sum(len(batch['id']) for batch in loader)
    snap = loader.telemetry_snapshot()
    reader.stop()
    reader.join()
    assert rows == 32
    hists = snap['histograms']
    assert hists['shuffle_wait']['count'] >= 4   # loader stage
    assert hists['collate']['count'] > 0         # loader stage
    assert hists['decode']['count'] > 0          # worker stage, via the reader
