"""Pool/ventilator tests with stub workers (model: petastorm/workers_pool/tests/ —
stub_workers.py + test_workers_pool.py + test_ventilator.py)."""

import threading
import time

import pytest

from petastorm_tpu.workers import EmptyResultError
from petastorm_tpu.workers.dummy_pool import DummyPool
from petastorm_tpu.workers.thread_pool import ThreadPool
from petastorm_tpu.workers.ventilator import ConcurrentVentilator
from petastorm_tpu.workers.worker_base import WorkerBase


class MultiplierWorker(WorkerBase):
    """Publishes value * coefficient (model: CoeffMultiplierWorker)."""

    def process(self, value):
        self.publish_func(value * self.args['coeff'])


class FailingWorker(WorkerBase):
    def process(self, value):
        if value == 5:
            raise ValueError('worker failure on 5')
        self.publish_func(value)


class SlowWorker(WorkerBase):
    def process(self, value):
        time.sleep(0.01)
        self.publish_func(value)


POOLS = [lambda: ThreadPool(3, results_queue_size=10), lambda: DummyPool()]


def _drain(pool):
    results = []
    while True:
        try:
            results.append(pool.get_results())
        except EmptyResultError:
            return results


@pytest.mark.parametrize('pool_factory', POOLS)
def test_pool_processes_all_items(pool_factory):
    pool = pool_factory()
    items = [{'value': i} for i in range(20)]
    ventilator = ConcurrentVentilator(pool.ventilate, items)
    pool.start(MultiplierWorker, {'coeff': 3}, ventilator)
    results = _drain(pool)
    assert sorted(results) == [i * 3 for i in range(20)]
    pool.stop()
    pool.join()


@pytest.mark.parametrize('pool_factory', POOLS)
def test_pool_exception_propagates(pool_factory):
    pool = pool_factory()
    items = [{'value': i} for i in range(10)]
    ventilator = ConcurrentVentilator(pool.ventilate, items)
    pool.start(FailingWorker, None, ventilator)
    with pytest.raises(ValueError, match='worker failure on 5'):
        _drain(pool)
    pool.stop()
    pool.join()


def test_pool_empty_ventilation():
    pool = ThreadPool(2)
    ventilator = ConcurrentVentilator(pool.ventilate, [])
    pool.start(MultiplierWorker, {'coeff': 1}, ventilator)
    with pytest.raises(EmptyResultError):
        pool.get_results()
    pool.stop()
    pool.join()


def test_pool_backpressure_bounded_queue():
    """Workers must not run unboundedly ahead of the consumer."""
    pool = ThreadPool(2, results_queue_size=5)
    items = [{'value': i} for i in range(100)]
    ventilator = ConcurrentVentilator(pool.ventilate, items,
                                      max_ventilation_queue_size=4)
    pool.start(SlowWorker, None, ventilator)
    time.sleep(0.3)
    # queue is bounded at 5; in-flight at 4 — far fewer than 100 items processed
    assert pool.diagnostics['output_queue_size'] <= 5
    results = _drain(pool)
    assert len(results) == 100
    pool.stop()
    pool.join()


def test_pool_stop_midway_no_deadlock():
    pool = ThreadPool(2, results_queue_size=2)
    items = [{'value': i} for i in range(200)]
    ventilator = ConcurrentVentilator(pool.ventilate, items)
    pool.start(SlowWorker, None, ventilator)
    pool.get_results()
    pool.stop()
    pool.join()  # must not hang


def test_multiple_epochs_ventilation():
    pool = ThreadPool(2)
    items = [{'value': i} for i in range(5)]
    ventilator = ConcurrentVentilator(pool.ventilate, items, iterations=3)
    pool.start(MultiplierWorker, {'coeff': 1}, ventilator)
    results = _drain(pool)
    assert len(results) == 15
    pool.stop()
    pool.join()


def test_ventilator_randomized_order_seeded():
    order1, order2 = [], []
    for order in (order1, order2):
        done = threading.Event()
        items = [{'value': i} for i in range(30)]

        def consume(value, _order=order):
            _order.append(value)
            if len(_order) == 60:
                done.set()

        v = ConcurrentVentilator(consume, items, iterations=2,
                                 randomize_item_order=True, random_seed=99)
        # consume synchronously: ventilate_fn appends directly; ack everything
        v.start()
        for _ in range(200):
            if done.is_set():
                break
            v.processed_item()
            time.sleep(0.005)
        v.stop()
    assert order1 == order2
    assert order1[:30] != sorted(order1[:30])  # actually shuffled


def test_ventilator_reset_after_completion():
    results = []
    v = ConcurrentVentilator(lambda value: results.append(value),
                             [{'value': i} for i in range(3)], iterations=1,
                             max_ventilation_queue_size=100)
    v.start()
    deadline = time.time() + 5
    while not v.completed() and time.time() < deadline:
        while len(results) > sum(1 for _ in range(0)):
            break
        for _ in range(len(results)):
            pass
        # ack everything seen so far
        for _ in range(len(results)):
            v.processed_item()
        time.sleep(0.01)
    for _ in range(10):
        v.processed_item()
    assert v.completed()
    v.reset()
    deadline = time.time() + 5
    while len(results) < 6 and time.time() < deadline:
        for _ in range(3):
            v.processed_item()
        time.sleep(0.01)
    assert len(results) == 6
    v.stop()


def test_ventilator_rejects_bad_iterations():
    with pytest.raises(ValueError):
        ConcurrentVentilator(lambda **kw: None, [], iterations=0)
    with pytest.raises(ValueError):
        ConcurrentVentilator(lambda **kw: None, [], iterations=-1)
