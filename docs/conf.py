# Sphinx configuration (reference parity: /root/reference/docs/conf.py +
# readthedocs.yml). The hand-written markdown (api.md, architecture.md, ...) is the
# primary documentation; this build adds the rendered-autodoc surface the reference
# publishes on readthedocs. Build: `sphinx-build -b html docs docs/_build` (CI docs
# job; sphinx is not installed in the dev image — the machine-checked docstring
# gate there is tests/test_doc_coverage.py).
import os
import sys

sys.path.insert(0, os.path.abspath('..'))

project = 'petastorm-tpu'
author = 'petastorm-tpu developers'
copyright = '2026, petastorm-tpu developers'

extensions = [
    'sphinx.ext.autodoc',
    'sphinx.ext.autosummary',
    'sphinx.ext.napoleon',
    'sphinx.ext.viewcode',
    'myst_parser',
]

autosummary_generate = True
autodoc_member_order = 'bysource'
autodoc_default_options = {
    'members': True,
    'undoc-members': False,
    'show-inheritance': True,
}
# Heavyweight optional backends are mocked so the docs build needs no TPU, TF,
# torch, or Spark runtime (readthedocs.yml's OOM note is the cautionary tale).
autodoc_mock_imports = ['tensorflow', 'torch', 'pyspark', 'zmq', 'psutil', 'dill',
                        'orbax', 'PIL']

source_suffix = {'.rst': 'restructuredtext', '.md': 'markdown'}
master_doc = 'index'
exclude_patterns = ['_build']
html_theme = 'alabaster'
